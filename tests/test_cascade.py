"""Tiered corpus cascade (ISSUE 14, ops/cascade.py): correctness of the
sketch -> int8 -> fp pipeline and its beyond-HBM host tiers.

Contracts pinned here:

* budget semantics — validated, power-of-two quantized, a budget
  covering the corpus composes the tier out;
* budget-sweep recall floors vs the exact oracle (Wilson CIs);
* tombstone + delta-shard visibility through EVERY tier;
* host-tier fp fetch bit-identical to the device-resident re-rank;
* off-parity — CascadeSearch=0 builds nothing, results and serve bytes
  byte-identical (the ci_check.sh standalone pass keys on "off_parity"
  / "parity" in these names);
* cost-ledger crosscheck ±15% for the new ops.cascade kernel families;
* qualmon tier triage verdicts (sketch_budget / int8_budget /
  host_fetch_drop);
* SketchRerank calibration persistence (save/load skips the
  recalibration scan; mutation invalidates).
"""

import socket

import numpy as np
import pytest

import sptag_tpu as sp
from sptag_tpu.core.types import DistCalcMethod
from sptag_tpu.ops import cascade
from sptag_tpu.utils import devmem, qualmon

jnp = pytest.importorskip("jax.numpy")


def _dataset(n=3000, d=48, nq=64, seed=7):
    rng = np.random.default_rng(seed)
    # mild clustering so the sketch tier has structure to exploit
    centers = rng.standard_normal((16, d)).astype(np.float32) * 2.0
    data = (centers[rng.integers(0, 16, n)]
            + rng.standard_normal((n, d)).astype(np.float32))
    queries = (centers[rng.integers(0, 16, nq)]
               + rng.standard_normal((nq, d)).astype(np.float32))
    return data.astype(np.float32), queries.astype(np.float32)


def _flat(data, **params):
    idx = sp.create_instance("FLAT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    for k, v in params.items():
        idx.set_parameter(k, str(v))
    idx.build(data)
    return idx


def _recall(ids, truth, k):
    hits = sum(len(set(map(int, ids[r][:k])) & set(map(int, truth[r][:k])))
               for r in range(len(ids)))
    return hits / float(len(ids) * k)


# ---------------------------------------------------------------------------
# budget + tier validation
# ---------------------------------------------------------------------------

def test_budget_resolution_and_validation():
    # auto budgets: pow2, ordered, clamped
    b1, b2 = cascade.resolve_budgets(0, 0, 10, 4096)
    assert b1 & (b1 - 1) == 0 and b2 & (b2 - 1) == 0
    assert 10 <= b2 <= b1 <= 4096
    # explicit budgets quantize UP, never shrink below k
    b1, b2 = cascade.resolve_budgets(300, 33, 10, 4096)
    assert (b1, b2) == (512, 64)
    # b2 is clamped to b1, both to n
    b1, b2 = cascade.resolve_budgets(100000, 100000, 10, 4096)
    assert (b1, b2) == (4096, 4096)
    with pytest.raises(ValueError):
        cascade.resolve_budgets(-1, 0, 10, 4096)
    with pytest.raises(ValueError):
        cascade.resolve_budgets(0, -5, 10, 4096)
    with pytest.raises(ValueError):
        cascade.normalize_tier("hbm")
    assert cascade.normalize_tier(" Host ") == "host"


def test_int8_quantization_contract():
    rng = np.random.default_rng(0)
    data = rng.standard_normal((64, 16)).astype(np.float32)
    q, scale = cascade.quantize_int8(data)
    assert q.dtype == np.int8
    np.testing.assert_allclose(q.astype(np.float32) * scale, data,
                               atol=scale)
    with pytest.raises(ValueError):
        cascade.quantize_int8(np.zeros((4, 4), np.int8))


# ---------------------------------------------------------------------------
# budget-sweep recall floors vs the exact oracle (Wilson CI)
# ---------------------------------------------------------------------------

def test_budget_sweep_recall_floors():
    data, queries = _dataset()
    k = 10
    base = _flat(data)
    truth_d, truth_i = base.search_batch(queries, k)
    last = 0.0
    for b1, b2, floor in [(256, 64, 0.55), (1024, 256, 0.80),
                          (3072, 1024, 0.90)]:
        idx = _flat(data, CascadeSearch=1, TierBudgetSketch=b1,
                    TierBudgetInt8=b2)
        _, ids = idx.search_batch(queries, k)
        rec = _recall(ids, truth_i, k)
        trials = len(queries) * k
        lo, hi = qualmon.wilson(rec * trials, trials)
        assert hi >= floor, (b1, b2, rec, lo, hi)
        # recall is monotone-ish in budget: generous budgets must not
        # fall below what starved ones achieved (allow CI slack)
        assert rec >= last - 0.05, (b1, b2, rec, last)
        last = rec
    # budgets covering the corpus = exact scan, recall 1.0 bit-exact
    idx = _flat(data, CascadeSearch=1, TierBudgetSketch=100000,
                TierBudgetInt8=100000)
    d, ids = idx.search_batch(queries, k)
    assert _recall(ids, truth_i, k) == 1.0
    np.testing.assert_array_equal(ids, truth_i)
    np.testing.assert_allclose(d, truth_d, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# tier parity: host fetch bit-identical to device-resident re-rank
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("tier", ["host", "host_all"])
def test_host_tier_bit_identical_to_device(tier):
    data, queries = _dataset(n=2000, nq=32)
    dev = _flat(data, CascadeSearch=1, TierBudgetSketch=512,
                TierBudgetInt8=128)
    d0, i0 = dev.search_batch(queries, 10)
    host = _flat(data, CascadeSearch=1, TierBudgetSketch=512,
                 TierBudgetInt8=128, CorpusTier=tier)
    d1, i1 = host.search_batch(queries, 10)
    np.testing.assert_array_equal(i0, i1)
    # the fp re-rank is ONE traced function for both tiers
    # (cascade.rerank_gathered) — distances agree bit for bit
    assert d0.tobytes() == d1.tobytes()


def test_host_tier_zero_fp_device_residency():
    data, queries = _dataset(n=2000, nq=32)
    devmem.reset()
    try:
        idx = _flat(data, CascadeSearch=1, CorpusTier="host")
        idx.search_batch(queries, 10)
        comp = devmem.component_bytes()
        # sketches + int8 on device, the fp corpus host-side ONLY
        assert "corpus" not in comp, comp
        assert comp.get("int8_blocks", 0) > 0
        assert comp.get("sketch", 0) > 0
        assert comp.get("host_corpus", 0) >= data.nbytes
        # host_all additionally evicts the int8 blocks
        devmem.reset()
        idx2 = _flat(data, CascadeSearch=1, CorpusTier="host_all")
        idx2.search_batch(queries, 10)
        comp2 = devmem.component_bytes()
        assert "corpus" not in comp2 and "int8_blocks" not in comp2, comp2
        assert comp2.get("host_corpus", 0) > comp.get("host_corpus", 0)
    finally:
        devmem.reset()


def test_host_tier_oracle_streams_blocks():
    """exact_search_batch on a host-tier index is exact (equal to the
    device oracle) and never materializes the fp corpus."""
    data, queries = _dataset(n=2000, nq=16)
    base = _flat(data)
    td, ti = base.exact_search_batch(queries, 10)
    host = _flat(data, CascadeSearch=1, CorpusTier="host")
    hd, hi = host.exact_search_batch(queries, 10)
    np.testing.assert_array_equal(ti, hi)
    np.testing.assert_allclose(td, hd, rtol=1e-5, atol=1e-5)
    # streamed merge with a tiny block size crosses block boundaries
    st = host._cascade_state()
    bd, bi = cascade.host_exact_scan(
        st.fp_host, np.asarray(st.invalid_d), queries, 10,
        int(DistCalcMethod.L2), 1, block_rows=257)
    np.testing.assert_array_equal(bi, ti)


# ---------------------------------------------------------------------------
# tombstones + delta shard through every tier
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("tier", ["device", "host", "host_all"])
def test_tombstones_visible_through_tiers(tier):
    data, queries = _dataset(n=1500, nq=16)
    idx = _flat(data, CascadeSearch=1, TierBudgetSketch=512,
                TierBudgetInt8=128, CorpusTier=tier)
    _, before = idx.search_batch(queries, 10)
    victims = sorted({int(v) for v in before[:, :3].ravel()
                      if v >= 0})[:16]
    assert idx.delete(data[victims]) == sp.ErrorCode.Success
    _, after = idx.search_batch(queries, 10)
    assert not (set(victims) & {int(v) for v in after.ravel()}), victims
    # exact oracle agrees the deletes are gone
    _, oracle = idx.exact_search_batch(queries, 10)
    assert not (set(victims) & {int(v) for v in oracle.ravel()})


@pytest.mark.parametrize("tier", ["device", "host"])
def test_delta_shard_adds_visible_through_tiers(tier):
    data, queries = _dataset(n=1500, nq=8)
    idx = _flat(data, CascadeSearch=1, CorpusTier=tier,
                DeltaShardCapacity=64)
    # plant rows identical to queries: they MUST surface at rank 0
    assert idx.add(queries[:4]) == sp.ErrorCode.Success
    d, ids = idx.search_batch(queries[:4], 5)
    n0 = 1500
    for r in range(4):
        assert ids[r, 0] >= n0, (r, ids[r])
        assert d[r, 0] <= 1e-4


# ---------------------------------------------------------------------------
# off-parity: CascadeSearch=0 is byte-identical and builds nothing
# ---------------------------------------------------------------------------

def test_cascade_off_parity_results_and_state():
    data, queries = _dataset(n=1200, nq=16)
    plain = _flat(data)
    d0, i0 = plain.search_batch(queries, 10)
    devmem.reset()
    try:
        off = _flat(data)       # defaults: CascadeSearch=0
        assert str(off.get_parameter("CascadeSearch")) == "0"
        assert str(off.get_parameter("CorpusTier")) == "device"
        d1, i1 = off.search_batch(queries, 10)
        assert d0.tobytes() == d1.tobytes()
        assert i0.tobytes() == i1.tobytes()
        comp = devmem.component_bytes()
        assert "int8_blocks" not in comp and "host_corpus" not in comp
        assert off._cascade is None
    finally:
        devmem.reset()


def test_cascade_off_parity_golden_wire_bytes():
    """Default knobs: a served response is byte-identical to the
    reference wire layout (the pattern every off-by-default subsystem
    carries; tools/ci_check.sh standalone)."""
    from conftest import ServerThread
    from sptag_tpu.serve import wire
    from sptag_tpu.serve.server import SearchServer
    from sptag_tpu.serve.service import (SearchExecutor, ServiceContext,
                                         ServiceSettings)

    rng = np.random.default_rng(13)
    data = rng.standard_normal((200, 12)).astype(np.float32)
    flat = sp.create_instance("FLAT", "Float")
    flat.set_parameter("DistCalcMethod", "L2")
    flat.build(data)
    ctx = ServiceContext(ServiceSettings(default_max_result=5))
    ctx.add_index("f", flat)
    server = SearchServer(ctx, batch_window_ms=1.0)
    t = ServerThread(server)
    t.start()
    host, port = t.wait_ready()
    try:
        qtext = "|".join(str(x) for x in data[3])
        expected_result = SearchExecutor(ctx).execute(qtext)
        expected_result.request_id = ""
        expected_body = expected_result.pack()
        expected = wire.PacketHeader(
            wire.PacketType.SearchResponse, wire.PacketProcessStatus.Ok,
            len(expected_body), 1, 99).pack() + expected_body
        body = wire.RemoteQuery(qtext).pack()
        s = socket.create_connection((host, port), timeout=10)
        s.sendall(wire.PacketHeader(
            wire.PacketType.SearchRequest, wire.PacketProcessStatus.Ok,
            len(body), 0, 99).pack() + body)
        s.settimeout(10)
        got = b""
        while len(got) < len(expected):
            chunk = s.recv(65536)
            if not chunk:
                break
            got += chunk
        s.close()
        assert got == expected
    finally:
        t.stop()


# ---------------------------------------------------------------------------
# qualmon tier triage
# ---------------------------------------------------------------------------

def test_classify_low_recall_names_starved_tier():
    v, _ = qualmon.classify_low_recall(
        "", "flat", cascade={"sketch_dropped": 3, "int8_dropped": 1,
                             "host_dropped": 0})
    assert v == "sketch_budget"
    v, _ = qualmon.classify_low_recall(
        "", "flat", cascade={"sketch_dropped": 1, "int8_dropped": 4,
                             "host_dropped": 0})
    assert v == "int8_budget"
    # a MEASURED budget starvation outranks the lifetime fetch-drop
    # counter (the triage re-ran this query's shortlists; host_dropped
    # is historical and must not mask the budget root cause)
    v, _ = qualmon.classify_low_recall(
        "", "flat", cascade={"sketch_dropped": 5, "int8_dropped": 0,
                             "host_dropped": 2})
    assert v == "sketch_budget"
    # shortlists clean + drops recorded -> the fetch is the suspect
    v, _ = qualmon.classify_low_recall(
        "", "flat", cascade={"sketch_dropped": 0, "int8_dropped": 0,
                             "host_dropped": 2})
    assert v == "host_fetch_drop"
    # all tiers clean -> fall through to the legacy verdicts
    v, _ = qualmon.classify_low_recall(
        "", "flat", cascade={"sketch_dropped": 0, "int8_dropped": 0,
                             "host_dropped": 0})
    assert v == "unknown"


def test_cascade_triage_counts_tier_drops():
    data, queries = _dataset(n=2000, nq=4)
    idx = _flat(data, CascadeSearch=1, TierBudgetSketch=64,
                TierBudgetInt8=16)
    _, truth = idx.exact_search_batch(queries[:1], 10)
    tri = idx.cascade_triage(queries[0], truth[0], 10)
    assert set(tri) == {"sketch_dropped", "int8_dropped", "host_dropped"}
    assert all(v >= 0 for v in tri.values())
    # off index reports nothing
    off = _flat(data)
    assert off.cascade_triage(queries[0], truth[0], 10) is None


# ---------------------------------------------------------------------------
# cost ledger crosscheck (the ops.cascade family; ±15%)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("Q,N,D,b1,b2,k", [(32, 2048, 64, 256, 64, 10)])
def test_crosscheck_cascade_kernels(Q, N, D, b1, b2, k):
    from sptag_tpu.utils import costmodel

    W = (D + 31) // 32
    metric, base = int(DistCalcMethod.L2), 1
    fp = jnp.zeros((N, D))
    i8 = jnp.zeros((N, D), jnp.int8)
    sk = jnp.zeros((N, W), jnp.int32)
    mean = jnp.zeros((D,))
    inv = jnp.zeros((N,), bool)
    scale = jnp.float32(0.01)
    q = jnp.zeros((Q, D))

    def close(family, compiled, **shape):
        rel = costmodel.crosscheck(family, compiled, **shape)
        assert abs(rel["flops_rel"]) <= 0.15, (family, rel)
        assert abs(rel["bytes_rel"]) <= 0.15, (family, rel)

    c = cascade._cascade_search_kernel.lower(
        fp, i8, sk, mean, inv, scale, q, k, b1, b2, metric, base,
        True, True).compile()
    close("cascade.search", c, Q=Q, N=N, W=W, D=D, b1=b1, b2=b2, k=k)
    c = cascade._cascade_search_kernel.lower(
        fp, i8, sk, mean, inv, scale, q, k, b1, b2, metric, base,
        False, True).compile()
    close("cascade.search", c, Q=Q, N=N, W=W, D=D, b1=b1, b2=b2, k=k,
          use_sketch=False)
    c = cascade._cascade_shortlist_kernel.lower(
        i8, sk, mean, inv, scale, q, b1, b2, metric, base, True).compile()
    close("cascade.shortlist", c, Q=Q, N=N, W=W, D=D, b1=b1, b2=b2)
    c = cascade._sketch_shortlist_kernel.lower(sk, mean, inv, q,
                                               b1).compile()
    close("cascade.sketch_shortlist", c, Q=Q, N=N, W=W, b1=b1)
    c = cascade._int8_rerank_kernel.lower(
        q, jnp.zeros((Q, b1, D), jnp.int8),
        jnp.zeros((Q, b1), jnp.int32), scale, b2, metric, base).compile()
    close("cascade.int8_rerank", c, Q=Q, D=D, b1=b1, b2=b2)
    c = cascade._fp_rerank_kernel.lower(
        q, jnp.zeros((Q, b2, D)), jnp.zeros((Q, b2), jnp.int32), k,
        metric, base).compile()
    close("cascade.rerank", c, Q=Q, D=D, b2=b2, k=k)
    c = cascade._fp_rerank_resident_kernel.lower(
        fp, q, jnp.zeros((Q, b2), jnp.int32), k, metric, base).compile()
    close("cascade.rerank_resident", c, Q=Q, N=N, D=D, b2=b2, k=k)
    R = 1024
    c = cascade._host_scan_block_kernel.lower(
        jnp.zeros((R, D)), jnp.zeros((R,), bool), q, k, metric,
        base).compile()
    close("cascade.host_scan", c, Q=Q, R=R, D=D, k=k)


# ---------------------------------------------------------------------------
# SketchRerank calibration persistence (save/load satellite)
# ---------------------------------------------------------------------------

def test_sketch_calibration_persisted_across_save_load(tmp_path):
    data, queries = _dataset(n=1500, nq=8)
    idx = _flat(data, SketchPrefilter=True)
    idx.search_batch(queries, 10)            # triggers the calibration
    with idx._lock:
        cal = idx._sketch[3]
    assert cal and cal > 0
    folder = str(tmp_path / "idx")
    assert idx.save_index(folder) == sp.ErrorCode.Success

    from sptag_tpu.algo.flat import FlatIndex
    from sptag_tpu.core.index import load_index

    loaded = load_index(folder)
    assert loaded._loaded_cal is not None
    assert loaded._loaded_cal[2] == cal
    # a warm start consumes the persisted value WITHOUT re-running the
    # calibration scan
    calls = []
    orig = FlatIndex._calibrate

    def spy(self, *a, **kw):
        calls.append(1)
        return orig(self, *a, **kw)

    FlatIndex._calibrate = spy
    try:
        loaded.search_batch(queries, 10)
        assert not calls, "persisted calibration must skip the scan"
        with loaded._lock:
            assert loaded._sketch[3] == cal
        # mutation invalidates: the next cold calibration re-runs
        assert loaded.add(queries[:1]) == sp.ErrorCode.Success
        assert loaded._loaded_cal is None
        loaded.search_batch(queries, 10)
        assert calls, "mutated corpus must recalibrate"
    finally:
        FlatIndex._calibrate = orig


def test_calibration_blob_absent_by_default(tmp_path):
    data, _ = _dataset(n=1200, nq=4)
    idx = _flat(data)
    folder = str(tmp_path / "plain")
    assert idx.save_index(folder) == sp.ErrorCode.Success
    import os

    assert not os.path.exists(os.path.join(folder, "sketch_cal.bin"))


# ---------------------------------------------------------------------------
# graph engines: dense + beam cascade (device vs host parity)
# ---------------------------------------------------------------------------

def _bkt(data, **params):
    idx = sp.create_instance("BKT", "Float")
    for k, v in {"DistCalcMethod": "L2", "BKTKmeansK": "8",
                 "TPTNumber": "2", "RefineIterations": "1",
                 "FinalRefineSearchMode": "dense", **params}.items():
        idx.set_parameter(k, str(v))
    idx.build(data)
    return idx


def test_dense_cascade_device_host_parity_and_recall():
    data, queries = _dataset(n=1200, d=32, nq=16)
    idx = _bkt(data, SearchMode="dense", BuildGraph=0)
    _, truth = idx.exact_search_batch(queries, 10)
    _, ids_off = idx.search_batch(queries, 10, max_check=1024)
    rec_off = _recall(ids_off, truth, 10)
    idx.set_parameter("CascadeSearch", "1")
    idx.set_parameter("TierBudgetInt8", "128")
    d1, i1 = idx.search_batch(queries, 10, max_check=1024)
    rec_on = _recall(i1, truth, 10)
    assert rec_on >= rec_off - 0.1, (rec_on, rec_off)
    idx.set_parameter("CorpusTier", "host")
    d2, i2 = idx.search_batch(queries, 10, max_check=1024)
    np.testing.assert_array_equal(i1, i2)
    assert d1.tobytes() == d2.tobytes()


def test_beam_cascade_host_tier_parity():
    data, queries = _dataset(n=1200, d=32, nq=16)
    idx = _bkt(data, SearchMode="beam")
    _, truth = idx.exact_search_batch(queries, 10)
    idx.set_parameter("CascadeSearch", "1")
    idx.set_parameter("CorpusTier", "host")
    devmem.reset()
    try:
        d1, i1 = idx.search_batch(queries, 10, max_check=512)
        assert _recall(i1, truth, 10) >= 0.8
        comp = devmem.component_bytes()
        assert "corpus" not in comp, comp          # int8-only device
        assert comp.get("host_corpus", 0) > 0
        # host-tier oracle stays exact
        _, hi = idx.exact_search_batch(queries, 10)
        np.testing.assert_array_equal(hi, truth)
        # segmented execution parity (the scheduler contract)
        idx.set_parameter("BeamSegmentIters", "3")
        d2, i2 = idx.search_batch(queries, 10, max_check=512)
        np.testing.assert_array_equal(i1, i2)
        assert d1.tobytes() == d2.tobytes()
        # continuous-batching scheduler parity
        idx.set_parameter("BeamSegmentIters", "0")
        idx.set_parameter("ContinuousBatching", "1")
        d3, i3 = idx.search_batch(queries, 10, max_check=512)
        np.testing.assert_array_equal(i1, i3)
        assert d1.tobytes() == d3.tobytes()
    finally:
        devmem.reset()
        idx.close()


def test_kdt_seeded_cascade_both_tiers():
    """The KDT walk seeds from per-query kd-descent rows gathered off
    `data` — on the DEVICE tier those rows are fp and must NOT be
    dequantized (only the walk's int8 shadow is scaled); on the HOST
    tier they are int8 and MUST be.  Regression for both directions of
    the seed-scaling bug."""
    data, queries = _dataset(n=1000, d=32, nq=12)
    idx = sp.create_instance("KDT", "Float")
    for k, v in {"DistCalcMethod": "L2", "TPTNumber": "2",
                 "RefineIterations": "1",
                 "FinalRefineSearchMode": "dense"}.items():
        idx.set_parameter(k, str(v))
    idx.build(data)
    _, truth = idx.exact_search_batch(queries, 10)
    _, i0 = idx.search_batch(queries, 10, max_check=512)
    rec0 = _recall(i0, truth, 10)
    for tier in ("device", "host"):
        idx.set_parameter("CascadeSearch", "1")
        idx.set_parameter("CorpusTier", tier)
        _, i1 = idx.search_batch(queries, 10, max_check=512)
        assert _recall(i1, truth, 10) >= rec0 - 0.1, tier
    idx.close()


def test_mesh_cascade_scheduler_vs_monolithic_parity(host_mesh):
    from sptag_tpu.parallel.sharded import ShardedBKTIndex

    data, queries = _dataset(n=600, d=32, nq=8)
    sh = ShardedBKTIndex.build(
        data, params={"DistCalcMethod": "L2", "BKTKmeansK": "8",
                      "TPTNumber": "2", "RefineIterations": "1",
                      "FinalRefineSearchMode": "dense",
                      "CascadeSearch": "1"},
        mesh=host_mesh(2))
    assert sh.data_score is not None and sh.score_scale > 0
    d1, i1 = sh.search(queries, 10, max_check=256)
    sh.enable_continuous_batching(slots=32)
    futs = sh.submit_batch(queries, 10, max_check=256)
    res = [f.result() for f in futs]
    i2 = np.stack([r[1] for r in res])
    d2 = np.stack([r[0] for r in res])
    np.testing.assert_array_equal(i1, i2)
    assert d1.tobytes() == d2.tobytes()
    sh.retire_scheduler()


def test_mesh_rejects_host_tier(host_mesh):
    from sptag_tpu.parallel.sharded import ShardedBKTIndex

    data, _ = _dataset(n=400, d=32, nq=4)
    with pytest.raises(ValueError, match="single-chip"):
        ShardedBKTIndex.build(
            data, params={"DistCalcMethod": "L2", "BKTKmeansK": "8",
                          "TPTNumber": "2", "RefineIterations": "1",
                          "FinalRefineSearchMode": "dense",
                          "CascadeSearch": "1", "CorpusTier": "host"},
            mesh=host_mesh(2))
