"""BKTree build invariants + persistence round-trip.

Mirrors what the reference guarantees structurally (BKTree::BuildTrees,
/root/reference/AnnService/inc/Core/Common/BKTree.h:144-211): every sample
appears exactly once per tree as a node centerid, child ranges partition the
node array, and the on-disk format round-trips.
"""

import io

import numpy as np
import pytest

from sptag_tpu.trees.bktree import BKTree


def _collect_tree_centerids(tree, t):
    start = tree.tree_starts[t]
    end = (tree.tree_starts[t + 1] if t + 1 < len(tree.tree_starts)
           else len(tree.nodes))
    cids = []
    for ni in range(start, end):
        cid = int(tree.nodes["centerid"][ni])
        cids.append(cid)
    return cids


def test_every_sample_is_a_center_exactly_once():
    rng = np.random.default_rng(0)
    n, d = 500, 16
    data = rng.standard_normal((n, d)).astype(np.float32)
    tree = BKTree(tree_number=2, kmeans_k=8, leaf_size=4, samples=200,
                  lloyd_iterations=8, restarts=2)
    tree.build(data, seed=1)

    assert len(tree.tree_starts) == 2
    for t in range(2):
        cids = _collect_tree_centerids(tree, t)
        # root holds the sample count; sentinel holds -1
        assert cids[0] == n
        assert cids[-1] == -1
        samples = sorted(c for c in cids[1:-1] if 0 <= c < n)
        assert samples == list(range(n)), "each sample once per tree"


def test_child_ranges_wellformed():
    rng = np.random.default_rng(3)
    data = rng.standard_normal((300, 8)).astype(np.float32)
    tree = BKTree(tree_number=1, kmeans_k=4, leaf_size=4, samples=100,
                  lloyd_iterations=6, restarts=1)
    tree.build(data, seed=2)
    cs = tree.nodes["childStart"]
    ce = tree.nodes["childEnd"]
    nn = len(tree.nodes)
    internal = np.flatnonzero(cs > 0)
    assert len(internal) > 0
    for ni in internal:
        assert 0 < cs[ni] <= ce[ni] <= nn


def test_duplicate_samples_degenerate_cluster():
    # 40 identical vectors force the all-one-cluster path
    data = np.ones((40, 8), np.float32)
    tree = BKTree(tree_number=1, kmeans_k=4, leaf_size=4, samples=100,
                  lloyd_iterations=4, restarts=1)
    tree.build(data, seed=0)
    # duplicates map to a single retained center
    assert len(tree.sample_center_map) >= 40  # 39 dups + center back-pointer
    centers = {v for k, v in tree.sample_center_map.items() if k >= 0}
    assert len(centers) == 1


def test_save_load_roundtrip():
    rng = np.random.default_rng(5)
    data = rng.standard_normal((200, 12)).astype(np.float32)
    tree = BKTree(tree_number=2, kmeans_k=4, leaf_size=4, samples=64,
                  lloyd_iterations=4, restarts=1)
    tree.build(data, seed=7)
    buf = io.BytesIO()
    tree.save(buf)
    buf.seek(0)
    loaded = BKTree.load(buf)
    np.testing.assert_array_equal(loaded.tree_starts, tree.tree_starts)
    np.testing.assert_array_equal(loaded.nodes, tree.nodes)
    assert loaded.sample_center_map == tree.sample_center_map


def test_collect_pivots():
    rng = np.random.default_rng(9)
    n = 400
    data = rng.standard_normal((n, 8)).astype(np.float32)
    tree = BKTree(tree_number=1, kmeans_k=8, leaf_size=4, samples=200,
                  lloyd_iterations=6, restarts=1)
    tree.build(data, seed=3)
    piv = tree.collect_pivots(64)
    assert 0 < len(piv) <= 64
    assert np.all((piv >= 0) & (piv < n))
    assert len(np.unique(piv)) == len(piv)
