"""Index-level value-type parity: Int8 / UInt8 / Int16.

The reference instantiates every index for all four value types via
X-macros (/root/reference/AnnService/src/Core/BKT/BKTIndex.cpp:577-581);
kernel-level conventions are pinned by tests/test_distance.py and the
Float lifecycle by tests/test_bkt.py, but nothing exercised the integer
types through the full index lifecycle.  Recall is asserted against ground
truth computed under the INDEX's own convention (exact int32 dot for
int8/uint8, float32 accumulation for int16; cosine is base^2 - dot on
ingest-normalized rows, DistanceUtils.h:452,492,533).
"""

import numpy as np
import pytest

import sptag_tpu as sp
from sptag_tpu.ops.distance import normalize

_BASE = {"Int8": 127, "UInt8": 255, "Int16": 32767}


def _corpus(value_type, n=1500, d=32, seed=11):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((16, d)).astype(np.float32) * 4
    x = centers[rng.integers(0, 16, n)] + \
        rng.standard_normal((n, d)).astype(np.float32)
    if value_type == "UInt8":
        x = x - x.min()
        return np.clip(np.round(x / x.max() * 200), 0, 255).astype(np.uint8)
    scale = 100.0 / np.abs(x).max()
    dt = np.int8 if value_type == "Int8" else np.int16
    if value_type == "Int16":
        scale *= 200
    return np.round(x * scale).astype(dt)


def _truth(data, queries, metric, value_type, k=10):
    if metric == "L2":
        df = data.astype(np.float64)
        qf = queries.astype(np.float64)
        d2 = ((df ** 2).sum(1)[None, :]
              - 2.0 * qf @ df.T + (qf ** 2).sum(1)[:, None])
        return np.argsort(d2, axis=1, kind="stable")[:, :k]
    base = _BASE[value_type]
    stored = normalize(data, base).astype(np.int64)
    q = normalize(queries, base).astype(np.int64)
    sim = q @ stored.T
    return np.argsort(-sim, axis=1, kind="stable")[:, :k]


@pytest.mark.parametrize("value_type", ["Int8", "UInt8", "Int16"])
@pytest.mark.parametrize("metric", ["L2", "Cosine"])
def test_bkt_lifecycle_value_types(tmp_path, value_type, metric):
    data = _corpus(value_type)
    queries = data[:64]

    index = sp.create_instance("BKT", value_type)
    for name, value in [("DistCalcMethod", metric), ("BKTKmeansK", "8"),
                        ("TPTNumber", "4"), ("TPTLeafSize", "128"),
                        ("NeighborhoodSize", "16"), ("CEF", "64"),
                        ("MaxCheckForRefineGraph", "128"),
                        ("MaxCheck", "512"), ("RefineIterations", "1"),
                        ("Samples", "200")]:
        assert index.set_parameter(name, value)
    assert index.build(data) == sp.ErrorCode.Success
    assert index.num_samples == len(data)

    truth = _truth(data, queries, metric, value_type)
    _, ids = index.search_batch(queries, 10)
    rec = np.mean([len(set(ids[i][:10].tolist()) & set(truth[i]))
                   / 10 for i in range(len(queries))])
    floor = 0.75 if (value_type == "UInt8" and metric == "Cosine") else 0.85
    assert rec >= floor, (value_type, metric, rec)

    # save/load round trip preserves dtype and results
    folder = str(tmp_path / f"{value_type}_{metric}")
    assert index.save_index(folder) == sp.ErrorCode.Success
    loaded = sp.load_index(folder)
    assert loaded.value_type == sp.VectorValueType[value_type]
    _, ids2 = loaded.search_batch(queries[:8], 5)
    _, ids1 = index.search_batch(queries[:8], 5)
    assert (ids1 == ids2).all()
