"""Dense tree-partition search tests (TPU-first fast path, algo/dense.py)."""

import numpy as np

import sptag_tpu as sp
from sptag_tpu.algo.dense import DenseTreeSearcher, partition_from_tree
from sptag_tpu.core.types import DistCalcMethod
from sptag_tpu.trees.bktree import BKTree


def _corpus(n=800, d=12, seed=5):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((16, d)).astype(np.float32) * 4
    data = (centers[rng.integers(0, 16, n)]
            + rng.standard_normal((n, d)).astype(np.float32))
    return data


def test_partition_covers_every_id_once():
    data = _corpus()
    tree = BKTree(tree_number=1, kmeans_k=8, leaf_size=8, samples=100)
    tree.build(data)
    centers, clusters = partition_from_tree(tree, len(data), 64)
    all_ids = np.concatenate(clusters)
    assert sorted(all_ids.tolist()) == list(range(len(data)))
    assert len(centers) == len(clusters)
    # clusters respect the target within the k-means branching slack
    assert max(len(c) for c in clusters) <= 64 + 8


def test_dense_search_recall():
    data = _corpus()
    tree = BKTree(tree_number=1, kmeans_k=8, leaf_size=8, samples=100)
    tree.build(data)
    centers, clusters = partition_from_tree(tree, len(data), 64)
    searcher = DenseTreeSearcher(data, centers, clusters, None,
                                 DistCalcMethod.L2, 1)
    rng = np.random.default_rng(0)
    queries = data[rng.integers(0, len(data), 32)] \
        + rng.standard_normal((32, data.shape[1])).astype(np.float32) * 0.05
    d, ids = searcher.search(queries, k=10, max_check=512)

    diff = queries[:, None, :] - data[None, :, :]
    exact = np.sum(diff * diff, axis=-1)
    truth = np.argsort(exact, axis=1)[:, :10]
    recall = np.mean([len(set(ids[q].tolist()) & set(truth[q].tolist())) / 10
                      for q in range(32)])
    assert recall >= 0.95, recall
    assert np.all(np.diff(d, axis=1) >= -1e-4)


def test_dense_search_excludes_deleted():
    data = _corpus(n=300)
    tree = BKTree(tree_number=1, kmeans_k=8, leaf_size=8, samples=100)
    tree.build(data)
    centers, clusters = partition_from_tree(tree, len(data), 64)
    deleted = np.zeros(len(data), bool)
    deleted[:10] = True
    searcher = DenseTreeSearcher(data, centers, clusters, deleted,
                                 DistCalcMethod.L2, 1)
    d, ids = searcher.search(data[:10], k=3, max_check=300)
    assert not np.isin(ids, np.arange(10)).any()


def test_bkt_dense_after_add_covers_new_rows():
    data = _corpus(n=400)
    index = sp.create_instance("BKT", "Float")
    index.set_parameter("DistCalcMethod", "L2")
    for name, value in [("BKTKmeansK", "8"), ("TPTNumber", "4"),
                        ("TPTLeafSize", "64"), ("NeighborhoodSize", "16"),
                        ("CEF", "64"), ("AddCEF", "32"),
                        ("MaxCheckForRefineGraph", "128"),
                        ("MaxCheck", "512"), ("RefineIterations", "1"),
                        ("Samples", "100"), ("SearchMode", "dense"),
                        ("DenseClusterSize", "64"),
                        ("AddCountForRebuild", "1000")]:
        assert index.set_parameter(name, value)
    assert index.build(data) == sp.ErrorCode.Success
    rng = np.random.default_rng(3)
    new = data[:8] + rng.standard_normal((8, 12)).astype(np.float32) * 0.01
    # AddCountForRebuild=1000 -> tree NOT rebuilt; dense path must still
    # cover the appended rows via nearest-centroid assignment
    assert index.add(new) == sp.ErrorCode.Success
    _, ids = index.search_batch(new, 2)
    hit = np.mean([(400 + q) in ids[q] for q in range(8)])
    assert hit >= 0.9, (hit, ids)


def test_dense_grouped_probing():
    """Query-grouped probing (DenseQueryGroup) must match or beat the
    per-query kernel's recall at the same MaxCheck (each query is scored
    against the group union's U >= nprobe blocks) and handle non-multiple
    batch sizes via padding."""
    data = _corpus(n=2000, d=16, seed=9)
    tree = BKTree(tree_number=1, kmeans_k=8, leaf_size=8, samples=100)
    tree.build(data)
    centers, clusters = partition_from_tree(tree, len(data), 64)
    searcher = DenseTreeSearcher(data, centers, clusters, None,
                                 DistCalcMethod.L2, 1)
    rng = np.random.default_rng(1)
    # dense enough over the ~31 blocks that the adaptive cap keeps G >= the
    # f32 tile floor (8); deliberately not a padding bucket so the padding
    # mask (nq_valid) is exercised too
    nq = 131
    queries = data[rng.integers(0, len(data), nq)] \
        + rng.standard_normal((nq, 16)).astype(np.float32) * 0.05

    exact = ((queries ** 2).sum(1)[:, None] + (data ** 2).sum(1)[None, :]
             - 2.0 * (queries @ data.T))
    truth = np.argsort(exact, axis=1)[:, :10]

    def recall(ids):
        return np.mean([len(set(ids[q].tolist()) & set(truth[q].tolist()))
                        / 10 for q in range(nq)])

    d0, i0 = searcher.search(queries, k=10, max_check=256)
    # union_factor=8 drives U to the full block count (~31 here), so every
    # query is scored against EVERY block its ungrouped probe set covered
    # (and more): recall can only match or improve, structurally
    d1, i1 = searcher.search(queries, k=10, max_check=256,
                             group=8, union_factor=8)
    assert np.all(np.diff(d1, axis=1) >= -1e-4)
    r0, r1 = recall(i0), recall(i1)
    assert r1 >= r0 - 1e-9, (r0, r1)
    # the tighter default union (factor 2) trades a little per-query probe
    # coverage for speed — recall must stay in the same band
    _, i3 = searcher.search(queries, k=10, max_check=256,
                            group=8, union_factor=2)
    assert recall(i3) >= r0 - 0.05, (r0, recall(i3))
    # self-queries through the GROUPED path (batch dense enough that the
    # adaptive cap keeps G=8).  Only a query's rank-0 block is guaranteed
    # to survive the union cut, and a row's own block is not always its
    # nearest-centroid block — assert a high hit RATE, not exactness
    d_self, i_self = searcher.search(data[:128], k=1, group=8,
                                     max_check=256, union_factor=4)
    assert searcher.last_effective_group == 8
    hit = np.mean(i_self[:, 0] == np.arange(128))
    assert hit >= 0.95, (hit, i_self[:, 0])
    # a sparse 3-query batch demotes grouping (adaptive cap below the tile
    # floor) and still returns correct shapes through the per-query kernel
    d2, i2 = searcher.search(queries[:3], k=5, group=64, union_factor=2)
    assert searcher.last_effective_group == 0
    assert i2.shape == (3, 5) and (i2[:, 0] >= 0).all()
    # oversized union factor WITH grouping active: U is clamped to the
    # rank buffer's width (G*nprobe) and the cluster count — no top_k crash
    d4, i4 = searcher.search(queries, k=5, max_check=256,
                             group=16, union_factor=50)
    assert searcher.last_effective_group > 1
    assert i4.shape == (nq, 5) and (i4[:, 0] >= 0).all()


def test_dense_grouped_power_of_two_validation():
    data = _corpus(n=300)
    tree = BKTree(tree_number=1, kmeans_k=8, leaf_size=8, samples=100)
    tree.build(data)
    centers, clusters = partition_from_tree(tree, len(data), 64)
    searcher = DenseTreeSearcher(data, centers, clusters, None,
                                 DistCalcMethod.L2, 1)
    import pytest

    with pytest.raises(ValueError):
        searcher.search(data[:4], k=2, group=12)


def test_dense_replicas_closure_assignment():
    """DenseReplicas=2 packs boundary rows into their nearest other block
    (capped), improving recall at fixed MaxCheck without duplicate ids in
    results."""
    data = _corpus(n=3000, d=24)
    truth_d = (data ** 2).sum(1)[None, :] - 2.0 * (data[:64] @ data.T)
    truth = np.argsort(truth_d, axis=1)[:, :10]

    def build(reps):
        index = sp.create_instance("BKT", "Float")
        for name, value in [("DistCalcMethod", "L2"), ("BKTKmeansK", "8"),
                            ("TPTNumber", "2"), ("TPTLeafSize", "100"),
                            ("NeighborhoodSize", "8"), ("CEF", "32"),
                            ("MaxCheckForRefineGraph", "64"),
                            ("RefineIterations", "1"), ("Samples", "100"),
                            ("DenseClusterSize", "64"),
                            ("DenseReplicas", str(reps)),
                            ("MaxCheck", "256")]:
            index.set_parameter(name, value)
        assert index.build(data) == sp.ErrorCode.Success
        return index

    def recall(index):
        _, ids = index.search_batch(data[:64], 10)
        for row in ids:
            real = [x for x in row if x >= 0]
            assert len(real) == len(set(real)), row    # dedup holds
        return np.mean([len(set(ids[i]) & set(truth[i])) / 10
                        for i in range(64)])

    i1, i2 = build(1), build(2)
    r1, r2 = recall(i1), recall(i2)
    # the recall effect is corpus-dependent (P grows, nprobe shrinks, so
    # FEWER distinct blocks are probed at the same budget) — assert sane
    # floors and the mechanical invariants, not universal improvement
    assert r1 >= 0.9 and r2 >= 0.85, (r1, r2)
    # capped growth: padded block size at most ~2x the replica-free one
    d1 = i1._get_dense()
    d2 = i2._get_dense()
    assert d2.cluster_size <= 2 * d1.cluster_size + 32, (
        d1.cluster_size, d2.cluster_size)
    # replicas really are present: total occupied slots grow
    occ1 = int(np.asarray((d1.member_ids >= 0).sum()))
    occ2 = int(np.asarray((d2.member_ids >= 0).sum()))
    assert occ2 > occ1, (occ1, occ2)


def test_dense_param_change_after_search_takes_effect():
    """Dense-affecting params set AFTER a dense search must invalidate the
    materialized dense snapshot (VERDICT r4 item 3): before the fix,
    DenseReplicas/DenseClusterSize changes silently no-opped until the
    next unrelated mutation (the same silent-no-op class the beam engine
    params had — reference SetParameter semantics re-read config live,
    inc/Core/VectorIndex.h SetParameter)."""
    data = _corpus(n=3000, d=24)
    index = sp.create_instance("BKT", "Float")
    for name, value in [("DistCalcMethod", "L2"), ("BKTKmeansK", "8"),
                        ("TPTNumber", "2"), ("TPTLeafSize", "100"),
                        ("NeighborhoodSize", "8"), ("CEF", "32"),
                        ("MaxCheckForRefineGraph", "64"),
                        ("RefineIterations", "1"), ("Samples", "100"),
                        ("DenseClusterSize", "64"),
                        ("SearchMode", "dense"),
                        ("MaxCheck", "256")]:
        assert index.set_parameter(name, value)
    assert index.build(data) == sp.ErrorCode.Success

    _, ids1 = index.search_batch(data[:32], 10)
    snap1 = index._get_dense()
    assert snap1.replicas == 1
    occ1 = int(np.asarray((snap1.member_ids >= 0).sum()))

    # post-search knob change: snapshot must be dropped and rebuilt
    assert index.set_parameter("DenseReplicas", "2")
    assert index._dense is None, "DenseReplicas change must drop snapshot"
    _, ids2 = index.search_batch(data[:32], 10)
    snap2 = index._get_dense()
    assert snap2 is not snap1
    assert snap2.replicas == 2
    occ2 = int(np.asarray((snap2.member_ids >= 0).sum()))
    assert occ2 > occ1, (occ1, occ2)

    # DenseClusterSize is baked into the partition: same invalidation
    assert index.set_parameter("DenseClusterSize", "128")
    assert index._dense is None
    _, _ = index.search_batch(data[:32], 10)
    snap3 = index._get_dense()
    assert snap3 is not snap2
    assert snap3.cluster_size != snap2.cluster_size or (
        snap3.centers.shape != snap2.centers.shape)

    # live-read knobs need NO invalidation: setting them must not drop
    # the snapshot (rebuilds are expensive; only baked params pay it)
    assert index.set_parameter("DenseQueryGroup", "8")
    assert index._dense is snap3
