"""bench.py streaming contract: a parseable headline JSON line must be on
stdout BEFORE the run finishes, so an external kill (the round-3 failure:
driver timeout -> rc=124, empty stdout, parsed=null) still leaves the round
with a measured artifact.

The test launches the real watchdog parent on a tiny corpus, waits for the
first streamed JSON line, SIGKILLs the whole process group mid-run, and
asserts the captured line is a parseable measured headline.
"""

import json
import os
import signal
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def test_sigkill_mid_run_leaves_parsed_headline(tmp_path):
    env = dict(os.environ)
    env.update({
        "BENCH_PLATFORM": "cpu",          # skip accelerator probes
        "JAX_PLATFORMS": "cpu",
        "BENCH_BUDGET_S": "300",
        # isolate the bench child's compile cache from every other
        # process (enable_compile_cache honors this env var, so the
        # child cannot race the suite on a shared cache dir)
        "SPTAG_TPU_COMPILE_CACHE": str(tmp_path / "xla_cache"),
    })
    p = subprocess.Popen(
        [sys.executable, BENCH, "2000"], env=env, cwd=REPO,
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        start_new_session=True)          # own group: killpg reaps children
    first_line = None
    deadline = time.time() + 360
    try:
        while time.time() < deadline:
            line = p.stdout.readline()
            if not line:                 # parent exited before we killed it
                break
            line = line.strip()
            if line.startswith("{"):
                first_line = line
                break
        assert first_line is not None, \
            "no JSON line streamed before deadline"
    finally:
        try:                             # SIGKILL mid-run: no cleanup runs
            os.killpg(os.getpgid(p.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        p.wait(timeout=30)

    obj = json.loads(first_line)
    assert obj.get("value", 0) > 0, f"headline not measured: {obj}"
    assert "metric" in obj and "unit" in obj and "vs_baseline" in obj
    # the early line must be honest about being partial
    assert obj.get("partial") is True


def test_envelope_fits_worst_case():
    """The derived budgets must fit the envelope by construction:
    probes + TPU child + CPU child + margin <= BENCH_BUDGET_S (+small
    slack for the kill/join overhead between stages)."""
    import importlib.util

    env_keys = ("BENCH_BUDGET_S", "BENCH_PROBE_TIMEOUT_S",
                "BENCH_PROBE_RETRIES")
    saved = {k: os.environ.pop(k, None) for k in env_keys}
    try:
        spec = importlib.util.spec_from_file_location("bench_mod", BENCH)
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)
        budget = bench._BUDGET_S
        margin = 30.0
        cpu_reserve = min(600.0, max(120.0, budget * 0.35))
        tpu_timeout = max(60.0, budget - cpu_reserve - margin)
        cpu_timeout = max(90.0, budget - tpu_timeout - margin)
        # probes run INSIDE the TPU child's budget (probe_accelerator
        # guards on _remaining), so the parent-level sum is just:
        worst = tpu_timeout + cpu_timeout + margin
        assert worst <= budget + 90.0, (tpu_timeout, cpu_timeout, budget)
        # and the probe worst case fits inside the child budget
        probe_worst = (bench.PROBE_TIMEOUT_S * bench.PROBE_RETRIES
                       + 10.0 * bench.PROBE_RETRIES)
        child_budget = max(tpu_timeout - 30.0, 45.0)
        assert probe_worst < child_budget, (probe_worst, child_budget)
    finally:
        for k, v in saved.items():
            if v is not None:
                os.environ[k] = v
