"""Golden distance-kernel tests: batched XLA kernels vs naive scalar numpy.

Parity: /root/reference/Test/src/DistanceTest.cpp:8-57 — SIMD L2/cosine vs
naive scalar loops over random dims, for float/int8/int16 (uint8 added here),
with relative tolerance 1e-5.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from sptag_tpu.core.types import DistCalcMethod, VectorValueType, base_of
from sptag_tpu.ops import distance as D


def _naive_l2(a, b):
    d = a.astype(np.float64) - b.astype(np.float64)
    return float(np.sum(d * d))


def _naive_cosine(a, b, base):
    dot = float(np.dot(a.astype(np.float64), b.astype(np.float64)))
    return base * base - dot


def _rand(value_type, shape, rng):
    if value_type == VectorValueType.Float:
        return rng.standard_normal(shape).astype(np.float32)
    if value_type == VectorValueType.Int8:
        return rng.integers(-127, 128, shape, dtype=np.int8)
    if value_type == VectorValueType.UInt8:
        return rng.integers(0, 256, shape, dtype=np.uint8)
    return rng.integers(-3000, 3000, shape, dtype=np.int16)


VALUE_TYPES = [VectorValueType.Float, VectorValueType.Int8,
               VectorValueType.UInt8, VectorValueType.Int16]


@pytest.mark.parametrize("value_type", VALUE_TYPES)
@pytest.mark.parametrize("dim", [2, 31, 100, 128, 256])
def test_pairwise_matches_scalar(value_type, dim):
    rng = np.random.default_rng(dim * 10 + int(value_type))
    q = _rand(value_type, (5, dim), rng)
    x = _rand(value_type, (17, dim), rng)
    base = base_of(value_type)

    l2 = np.asarray(D.pairwise_distance(jnp.asarray(q), jnp.asarray(x),
                                        DistCalcMethod.L2, value_type))
    cos = np.asarray(D.pairwise_distance(jnp.asarray(q), jnp.asarray(x),
                                         DistCalcMethod.Cosine, value_type))
    for i in range(q.shape[0]):
        for j in range(x.shape[0]):
            ref_l2 = _naive_l2(q[i], x[j])
            ref_cos = _naive_cosine(q[i], x[j], base)
            assert l2[i, j] == pytest.approx(ref_l2, rel=2e-5, abs=1e-3)
            assert cos[i, j] == pytest.approx(ref_cos, rel=2e-5, abs=1e-3)


@pytest.mark.parametrize("value_type", VALUE_TYPES)
def test_batched_gathered_distance_matches_pairwise(value_type):
    rng = np.random.default_rng(int(value_type))
    q = _rand(value_type, (3, 24), rng)
    cand = _rand(value_type, (3, 9, 24), rng)
    base = base_of(value_type)
    for metric in (DistCalcMethod.L2, DistCalcMethod.Cosine):
        got = np.asarray(D.batched_gathered_distance(
            jnp.asarray(q), jnp.asarray(cand), metric, base))
        for i in range(3):
            want = np.asarray(D.pairwise_distance(
                jnp.asarray(q[i][None]), jnp.asarray(cand[i]), metric,
                value_type))[0]
            np.testing.assert_allclose(got[i], want, rtol=2e-5, atol=1e-3)


def test_int_cosine_base_constants():
    # The magic constants the reference hardcodes (DistanceUtils.h:452,492,533)
    assert base_of(VectorValueType.Int8) ** 2 == 16129
    assert base_of(VectorValueType.UInt8) ** 2 == 65025
    assert base_of(VectorValueType.Int16) ** 2 == 1073676289
    assert base_of(VectorValueType.Float) == 1


def test_normalize_parity():
    rng = np.random.default_rng(0)
    v = rng.standard_normal((4, 16)).astype(np.float32)
    out = D.normalize(v, 1)
    np.testing.assert_allclose(np.linalg.norm(out, axis=1), 1.0, rtol=1e-5)

    vi = rng.integers(-100, 100, (4, 16)).astype(np.int8)
    outi = D.normalize(vi, 127)
    norms = np.linalg.norm(outi.astype(np.float64), axis=1)
    # int rounding: norm close to base but not exact
    assert np.all(np.abs(norms - 127) < 16 * 0.5 * 4)

    # zero rows -> constant vector base/sqrt(D) (CommonUtils.h:101-103)
    z = np.zeros((1, 16), np.float32)
    outz = D.normalize(z, 1)
    np.testing.assert_allclose(outz, 1.0 / 4.0, rtol=1e-6)


def test_batch_topk_sorted_ascending():
    rng = np.random.default_rng(1)
    dmat = rng.standard_normal((3, 50)).astype(np.float32)
    dists, idx = D.batch_topk(jnp.asarray(dmat), 10)
    dists, idx = np.asarray(dists), np.asarray(idx)
    for r in range(3):
        order = np.sort(dmat[r])[:10]
        np.testing.assert_allclose(dists[r], order, rtol=1e-6)
        assert np.all(np.diff(dists[r]) >= 0)
        np.testing.assert_allclose(dmat[r][idx[r]], dists[r], rtol=1e-6)


def test_int16_full_range_no_overflow():
    """Raw full-range int16 L2 must not overflow: a single int16 product
    reaches 2^30, so int32 accumulation wraps (observed: -6.9e8 instead of
    3.6e9); the kernel accumulates int16 in float32 like the reference's
    SIMD path (lanes converted to float before the horizontal add)."""
    rng = np.random.default_rng(6)
    q = rng.integers(-32000, 32001, (4, 32)).astype(np.int16)
    x = rng.integers(-32000, 32001, (8, 32)).astype(np.int16)
    got = np.asarray(D.pairwise_dot(jnp.asarray(q), jnp.asarray(x)))
    want = q.astype(np.float64) @ x.T.astype(np.float64)
    # float32 accumulation of ~1e9-magnitude terms: wrapping would be off
    # by ~4e9, rounding by ~1e3 — the tolerance separates the two cleanly
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e4)

    dl2 = np.asarray(D.pairwise_l2(jnp.asarray(q), jnp.asarray(x)))
    wl2 = ((q.astype(np.float64)[:, None, :]
            - x.astype(np.float64)[None, :, :]) ** 2).sum(-1)
    np.testing.assert_allclose(dl2, wl2, rtol=1e-5)

    gb = np.asarray(D.batched_gathered_distance(
        jnp.asarray(q), jnp.asarray(np.broadcast_to(x[None, :4], (4, 4, 32))),
        0, 1))
    wb = ((q.astype(np.float64)[:, None, :]
           - x.astype(np.float64)[None, :4, :]) ** 2).sum(-1)
    np.testing.assert_allclose(gb, wb, rtol=1e-5)


def test_int16_exact_cosine_is_integer_exact():
    """Round-4 exact int16 (VERDICT item 5): the cosine convention
    ``1073676289 - dot`` computes ENTIRELY in int32 via the high/low byte
    split, so distances equal the exact int64 ground truth EXACTLY —
    the reference's own pair-exact-then-f32 path is looser (its measured
    A/B cost was direction-B recall 0.934, reports/AB_REFERENCE.md)."""
    rng = np.random.default_rng(9)
    base = base_of(VectorValueType.Int16)
    raw = rng.standard_normal((12, 48)).astype(np.float32)
    q = D.normalize(raw[:4].astype(np.int16) * 0 +
                    (raw[:4] * 3000).astype(np.int16), base)
    x = D.normalize((raw[4:] * 3000).astype(np.int16), base)
    got = np.asarray(D.pairwise_cosine(jnp.asarray(q), jnp.asarray(x),
                                       base))
    want_int = (int(base) ** 2
                - q.astype(np.int64) @ x.T.astype(np.int64))
    # the int32 computation is exact; the only rounding is the monotonic
    # final int32 -> float32 output conversion, so the result must equal
    # f32(exact integer) BITWISE — and ordering can merge ties but never
    # invert
    assert np.array_equal(got, want_int.astype(np.float32))

    # gathered variant agrees exactly too
    cand = np.broadcast_to(x[None, :4], (4, 4, 48)).copy()
    gg = np.asarray(D.batched_gathered_distance(
        jnp.asarray(q), jnp.asarray(cand), DistCalcMethod.Cosine, base))
    np.testing.assert_array_equal(gg, want_int[:, :4].astype(np.float32))


def test_int16_exact_l2_tighter_than_f32():
    """Exact-split int16 L2: each partial is int32-exact, only the final
    combine rounds — error vs the float64 truth is a few ULPs of the
    result, far inside the old per-product-f32 error envelope."""
    rng = np.random.default_rng(10)
    q = rng.integers(-32000, 32001, (6, 64)).astype(np.int16)
    x = rng.integers(-32000, 32001, (9, 64)).astype(np.int16)
    want_dot = q.astype(np.int64) @ x.T.astype(np.int64)
    got_dot = np.asarray(D.pairwise_dot(jnp.asarray(q), jnp.asarray(x)))
    err = np.abs(got_dot - want_dot)
    # one f32 rounding at result magnitude ~2^31: ulp ~256; allow a few
    assert err.max() <= 1024, err.max()

    assert D.int16_exact()
    D.set_int16_exact(False)
    try:
        loose = np.asarray(D.pairwise_dot(jnp.asarray(q), jnp.asarray(x)))
    finally:
        D.set_int16_exact(True)
    # plain f32 accumulation really is coarser on the same data
    assert np.abs(loose - want_dot).max() > err.max()

    # norms: exact split vs float64 truth
    n = np.asarray(D.row_sqnorms(jnp.asarray(x)))
    wn = (x.astype(np.int64) ** 2).sum(1)
    assert np.abs(n - wn).max() <= 4096      # one rounding at ~2^36
