"""Serving timeline + SLO burn-rate engine + ground-truth canary + mesh
skew telemetry (ISSUE 15).

Units: ring bounds and coarse downsampling, counter→rate math,
histogram extraction, the unified labeled-family sampling surface, the
SLO state machine under a fake clock (ok → warn → page → ok with flight
events), canary probe ground-truth parity vs the oracle, the
shard_skew triage verdict, and canary admission isolation.

E2e: a server-tier canary measuring exact recall 1.0 through the full
serve path; THE acceptance drill — an aggregator over two shards with a
fault-injected slow shard driving the latency objective to ``page``,
visible on /debug/slo, /metrics (slo_* gauges) and a flightrec
transition event, with the backend-skew family naming the slow shard;
and the mesh scheduler's per-shard iteration series in /debug/timeline.

Off-parity: with every ISSUE 15 knob at its default the serve wire
bytes are byte-identical, no sampler/prober thread exists and the
timeline counters read zero (the ci_check.sh standalone pass).
"""

import json
import logging
import socket
import threading
import time

import numpy as np
import pytest

import sptag_tpu as sp
from sptag_tpu.core.types import DistCalcMethod
from sptag_tpu.serve import canary as canary_mod
from sptag_tpu.serve import protocol, slo, wire
from sptag_tpu.serve.aggregator import (AggregatorContext,
                                        AggregatorService, RemoteServer)
from sptag_tpu.serve.server import SearchServer
from sptag_tpu.serve.service import (SearchExecutor, ServiceContext,
                                     ServiceSettings)
from sptag_tpu.utils import flightrec, metrics, qualmon, timeline

from conftest import ServerThread


def _http_get(port, path):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read().decode()
    conn.close()
    return resp.status, body


def _flat_index(n=60, d=8, seed=0):
    rng = np.random.default_rng(seed)
    data = rng.standard_normal((n, d)).astype(np.float32)
    idx = sp.create_instance("FLAT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    idx.build(data)
    return idx, data


# ---------------------------------------------------------------------------
# timeline store units
# ---------------------------------------------------------------------------

def test_ring_bounds_and_coarse_downsampling():
    """Fine rings are hard-bounded; every `coarse_every` fine points
    fold into one (mean, min, max) aggregate covering a longer horizon
    at the same fixed memory."""
    timeline.configure(enabled=True, capacity=8, coarse_every=4)
    for i in range(50):
        timeline.record("t.series", float(i), now=float(i))
    fine = timeline.points("t.series")
    assert len(fine) == 8                      # ring bound
    assert fine[-1] == (49.0, 49.0)
    coarse = timeline.points("t.series", coarse=True)
    assert 0 < len(coarse) <= 8
    # each coarse point is the mean of its 4-sample window
    t, mean = coarse[0][0], coarse[0][1]
    assert mean == pytest.approx(t - 1.5)      # mean(i-3..i) = i - 1.5


def test_counter_rate_and_histogram_extraction():
    """Counters become per-second rates against the previous tick;
    histograms contribute p50/p99 (ms) and an observation rate; gauges
    sample as-is."""
    timeline.configure(enabled=True)
    metrics.inc("t.ctr", 5)
    metrics.set_gauge("t.gauge", 7.5)
    assert timeline.sample_now(now=0.0) > 0
    assert timeline.latest("t.ctr.rate") is None     # first tick: no rate
    metrics.inc("t.ctr", 10)
    metrics.observe("t.lat", 0.050)
    timeline.sample_now(now=2.0)
    assert timeline.latest("t.ctr.rate") == pytest.approx(5.0)
    assert timeline.latest("t.gauge") == 7.5
    assert timeline.latest("t.lat.p99_ms") == pytest.approx(50.0)


def test_window_values_extend_into_coarse_ring():
    """A query window longer than the fine ring's span is covered by
    coarse means — the slow burn window's long-horizon path."""
    timeline.configure(enabled=True, capacity=8, coarse_every=2)
    for i in range(50):
        timeline.record("t.w", float(i), now=float(i))
    short = timeline.window_values("t.w", 5.0, now=49.0)
    assert short == [44.0, 45.0, 46.0, 47.0, 48.0, 49.0]
    long = timeline.window_values("t.w", 40.0, now=49.0)
    assert len(long) > 8                       # coarse entries prepended
    st = timeline.window_stats("t.w", 5.0, now=49.0)
    assert st["last"] == 49.0 and st["n"] == 6


def test_labeled_families_sampled_into_series():
    """The timeline samples the SAME labeled-series provider surface
    /metrics renders (the ISSUE 15 dedupe contract): a devmem component
    appears as its labeled series key."""
    from sptag_tpu.utils import devmem

    class Owner:
        pass

    o = Owner()
    devmem.track("corpus", o, 4096)
    timeline.configure(enabled=True)
    timeline.sample_now(now=1.0)
    key = 'memory.device_bytes{component="corpus"}'
    assert timeline.latest(key) == 4096.0
    assert key in timeline.snapshot()["series"]


def test_series_cap_counts_overflow():
    timeline.configure(enabled=True)
    base = timeline.counters()["series"]
    for i in range(timeline.MAX_SERIES + 5 - base):
        timeline.record("t.cap", float(i), label="i=%d" % i, now=0.0)
    c = timeline.counters()
    assert c["series"] <= timeline.MAX_SERIES
    assert c["series_dropped"] >= 5


def test_timeline_cli_sparkline_and_report():
    from sptag_tpu.tools import timeline as tlcli

    assert tlcli.sparkline([]) == ""
    assert tlcli.sparkline([1.0, 1.0]) == "▄▄"
    line = tlcli.sparkline(list(range(100)), width=10)
    assert len(line) == 10 and line[0] == "▁" and line[-1] == "█"
    timeline.configure(enabled=True)
    timeline.record("t.cli", 1.0, now=0.0)
    timeline.record("t.cli", 9.0, now=1.0)
    lines = tlcli.report(timeline.snapshot())
    assert any("t.cli" in ln and "max 9" in ln for ln in lines)


# ---------------------------------------------------------------------------
# SLO burn-rate state machine (fake clock)
# ---------------------------------------------------------------------------

def test_slo_burn_rate_state_machine_fake_clock():
    """Multi-window burn: warn needs BOTH windows over warn_burn, page
    needs both over page_burn, recovery drains through the fast window
    — each transition emits a flight event and bumps the counter."""
    timeline.configure(enabled=True, capacity=256)
    flightrec.configure(enabled=True)
    cfg = slo.SloConfig(availability_target=0.95, fast_window_s=10.0,
                        slow_window_s=30.0, warn_burn=1.0, page_burn=4.0)
    eng = slo.SloEngine(cfg, tier="server", clock=lambda: 0.0)
    # budget = 1 - 0.95 = 0.05 violating-sample fraction
    for t in range(30):
        timeline.record("canary.ok", 1.0, now=float(t))
    eng.evaluate(now=29.0)
    objs = eng.snapshot()["objectives"]
    assert objs["availability"]["state"] == "ok"
    # partial outage: 4 bad samples -> fast burn high, slow burn ~2.6
    for t in range(30, 34):
        timeline.record("canary.ok", 0.0, now=float(t))
    eng.evaluate(now=33.0)
    assert eng.snapshot()["objectives"]["availability"]["state"] == "warn"
    # sustained outage -> both windows past page_burn
    for t in range(34, 46):
        timeline.record("canary.ok", 0.0, now=float(t))
    eng.evaluate(now=45.0)
    snap = eng.snapshot()["objectives"]["availability"]
    assert snap["state"] == "page"
    assert snap["burn_fast"] >= cfg.page_burn
    assert snap["burn_slow"] >= cfg.page_burn
    # recovery: healthy long enough that BOTH windows drain
    for t in range(46, 90):
        timeline.record("canary.ok", 1.0, now=float(t))
    eng.evaluate(now=89.0)
    snap = eng.snapshot()["objectives"]["availability"]
    assert snap["state"] == "ok"
    assert snap["transitions"] == 3
    kinds = [e["payload"] for e in flightrec.collect()
             if e["kind"] == "slo_transition"]
    assert [(p["from"], p["to"]) for p in kinds] == [
        ("ok", "warn"), ("warn", "page"), ("page", "ok")]
    assert metrics.counter_value("slo.transitions") == 3
    # the labeled exposition carries the per-objective state
    text = metrics.render_provider_families()
    assert 'sptag_tpu_slo_state{objective="availability",tier="server"} 0' \
        in text


def test_slo_insufficient_samples_holds_state():
    """Too few fast-window samples must not flap the verdict — no data
    is not a page."""
    timeline.configure(enabled=True)
    eng = slo.SloEngine(slo.SloConfig(availability_target=0.99,
                                      fast_window_s=10.0,
                                      slow_window_s=30.0, min_samples=3),
                        clock=lambda: 0.0)
    timeline.record("canary.ok", 0.0, now=0.0)
    eng.evaluate(now=1.0)
    assert eng.snapshot()["objectives"]["availability"]["state"] == "ok"


def test_slo_config_from_settings_duck_types_both_tiers():
    s = ServiceSettings(slo_p99_ms=125.0, slo_fast_window_s=5.0)
    cfg = slo.config_from_settings(s)
    assert cfg.p99_ms == 125.0 and cfg.fast_window_s == 5.0
    assert slo.armed(cfg)
    assert not slo.armed(slo.config_from_settings(ServiceSettings()))
    a = AggregatorContext(slo_recall_floor=0.8)
    assert slo.armed(slo.config_from_settings(a))


# ---------------------------------------------------------------------------
# canary: ground truth parity + isolation
# ---------------------------------------------------------------------------

def test_canary_probe_truth_matches_oracle_exactly():
    """Pinned truth == the oracle's answer, and the probe text
    round-trips the exact float32 vector (the parity satellite)."""
    idx, data = _flat_index(n=50, d=8)
    ctx = ServiceContext(ServiceSettings())
    ctx.add_index("main", idx)
    probes = canary_mod.probes_from_context(ctx, count=6, k=5)
    assert len(probes) == 6
    for p in probes:
        parsed = protocol.parse_query(p.text)
        vec = parsed.extract_vector(idx.value_type, "|")
        assert vec is not None
        ex_d, ex_ids = idx.exact_search_batch(vec.reshape(1, -1), 5)
        assert p.truth_ids == [int(v) for v in ex_ids[0]]
        assert p.truth_dists == pytest.approx(
            [float(d) for d in ex_d[0]])
        assert parsed.result_num == 5          # $resultnum pins served k


def test_admission_canary_exempt_from_fair_shares():
    """A canary-flagged admit rides the state ladder but never charges
    the fair-share table (the isolation contract's admission half)."""
    from sptag_tpu.serve.admission import (ADMIT, DEGRADE,
                                           AdmissionConfig,
                                           AdmissionController)

    clock = [0.0]
    ctrl = AdmissionController(AdmissionConfig(), clock=lambda: clock[0])
    assert ctrl.admit("probe", canary=True) == ADMIT
    assert "probe" not in ctrl._clients        # never share-charged
    ctrl._state = 1                            # degrade state
    assert ctrl.admit("probe", canary=True) == DEGRADE
    assert "probe" not in ctrl._clients


def test_classify_low_recall_shard_skew_verdict():
    """A budget-exhausted sample whose per-shard iteration counters
    show a straggler is triaged shard_skew, naming the shard; balanced
    counters keep the beam_budget verdict."""
    flightrec.note_query_stats("rid-skew", iters=128, t_budget=128,
                               shard_imbalance=2.1, slow_shard=3)
    verdict, detail = qualmon.classify_low_recall("rid-skew", "beam")
    assert verdict == "shard_skew"
    assert "shard 3" in detail
    flightrec.note_query_stats("rid-flat", iters=128, t_budget=128,
                               shard_imbalance=1.05, slow_shard=0)
    verdict, _ = qualmon.classify_low_recall("rid-flat", "beam")
    assert verdict == "beam_budget"


def test_canary_e2e_server_tier_exact_recall_and_isolation(tmp_path):
    """Canary armed on a real server: probes replay through the full
    serve path, exact recall lands at 1.0 in the timeline and families,
    and — with qualmon armed at rate 1 — the live quality windows see
    ZERO samples (the isolation contract's qualmon half)."""
    idx, data = _flat_index()
    ctx = ServiceContext(ServiceSettings(default_max_result=5,
                                         canary_probes=4))
    ctx.add_index("main", idx)
    server = SearchServer(ctx, batch_window_ms=1.0,
                          timeline_interval_ms=50.0,
                          canary_interval_ms=30.0,
                          quality_sample_rate=1.0)
    t = ServerThread(server)
    t.start()
    t.wait_ready(60)
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            if metrics.counter_value("canary.probes") >= 5:
                break
            time.sleep(0.05)
        assert metrics.counter_value("canary.probes") >= 5
        assert metrics.counter_value("canary.failures") == 0
        assert timeline.latest("canary.recall") == 1.0
        assert timeline.latest("canary.ok") == 1.0
        snap = server._canary.snapshot()
        assert snap["indexes"]["main"]["recall_mean"] == 1.0
        # canary rids excluded from the live quality windows
        qualmon.drain()
        assert qualmon.window_stats() == {}
        text = metrics.render_provider_families()
        assert ('sptag_tpu_canary_recall{index="main",tier="server"} 1.0'
                in text)
    finally:
        t.stop()
    # the prober thread died with the server
    assert not any(th.name == "canary-prober"
                   for th in threading.enumerate())


# ---------------------------------------------------------------------------
# THE acceptance drill: fault-injected slow shard -> page
# ---------------------------------------------------------------------------

def _boot_shard(idx, name, fault_spec=None):
    ctx = ServiceContext(ServiceSettings(default_max_result=3))
    ctx.add_index(name, idx)
    srv = SearchServer(ctx, batch_window_ms=1.0, fault_spec=fault_spec)
    t = ServerThread(srv)
    t.start()
    return t, t.wait_ready(60)


@pytest.mark.locksan_ok
def test_e2e_drill_slow_shard_drives_page(tmp_path):
    """ISSUE 15 acceptance: a fault-injected slow shard drives the
    aggregator's latency objective to page — visible on /debug/slo,
    /metrics (slo_* gauges) and a flightrec transition event — while
    the backend-skew family names the slow shard."""
    idx, data = _flat_index(n=40, d=8)
    ta, (ha, pa) = _boot_shard(idx, "main")
    tb, (hb, pb) = _boot_shard(idx, "main",
                               fault_spec="delay@server.respond:ms=250,p=1")
    probe_file = tmp_path / "probes.txt"
    probe_file.write_text(
        "$resultnum:3 " + "|".join(repr(float(x)) for x in data[7]) + "\n")
    agg_ctx = AggregatorContext(
        search_timeout_s=30.0, metrics_port=-1,
        flight_recorder=True,
        timeline_interval_ms=100.0,
        slo_p99_ms=60.0, slo_fast_window_s=1.0, slo_slow_window_s=2.5,
        slo_warn_burn=1.0, slo_page_burn=4.0,
        canary_interval_ms=50.0, canary_probe_file=str(probe_file))
    agg_ctx.servers = [RemoteServer(ha, pa), RemoteServer(hb, pb)]
    agg = AggregatorService(agg_ctx)
    tg = ServerThread(agg)
    tg.start()
    tg.wait_ready(60)
    mport = agg._metrics_http.port
    try:
        deadline = time.time() + 30
        state = ""
        while time.time() < deadline:
            status, body = _http_get(mport, "/debug/slo")
            assert status == 200
            snap = json.loads(body)
            state = snap.get("objectives", {}).get(
                "latency_p99", {}).get("state", "")
            if state == "page":
                break
            time.sleep(0.1)
        assert state == "page", snap
        # canary picture rides the same page
        assert snap["canary"]["indexes"]["aggregator"]["probes"] > 0
        # /metrics: the slo_* gauges say page (code 2)
        status, text = _http_get(mport, "/metrics")
        assert status == 200
        assert ('sptag_tpu_slo_state{objective="latency_p99",'
                'tier="aggregator"} 2') in text
        # the backend-skew family names the slow shard as straggler
        slow = "%s:%d" % (hb, pb)
        assert ('sptag_tpu_aggregator_backend_straggler{backend="%s"} 1'
                % slow) in text
        # the flight ring carries the transition event
        status, body = _http_get(mport, "/debug/flight")
        assert status == 200
        trace_json = json.loads(body)
        trans = [e for e in trace_json["flightEvents"]
                 if e["kind"] == "slo_transition"]
        assert any(e["payload"]["to"] == "page" for e in trans)
        # /debug/timeline serves the canary + slo series history
        status, body = _http_get(mport, "/debug/timeline?series=canary")
        assert status == 200
        tl = json.loads(body)
        assert any(k.startswith("canary.latency_ms")
                   for k in tl["series"])
    finally:
        tg.stop()
        ta.stop()
        tb.stop()


# ---------------------------------------------------------------------------
# mesh shard-skew series
# ---------------------------------------------------------------------------

def test_mesh_scheduler_publishes_shard_skew_series(host_mesh):
    """The mesh scheduler's (cap, n_shards) iteration counters surface
    as per-shard labeled series the timeline records (the /debug/
    timeline acceptance surface) plus skew/straggler gauges, and every
    retired rid carries its per-shard imbalance stats."""
    from sptag_tpu.algo.scheduler import gather_futures
    from sptag_tpu.parallel.sharded import ShardedBKTIndex

    rng = np.random.default_rng(3)
    data = rng.standard_normal((128, 16)).astype(np.float32)
    index = ShardedBKTIndex.build(
        data, DistCalcMethod.L2, mesh=host_mesh(2),
        params={"BKTNumber": 1, "BKTKmeansK": 4, "TPTNumber": 2,
                "TPTLeafSize": 32, "NeighborhoodSize": 8, "CEF": 16,
                "MaxCheckForRefineGraph": 64, "RefineIterations": 1,
                "MaxCheck": 64, "SearchMode": "beam"})
    timeline.configure(enabled=True)
    index.enable_continuous_batching(slots=32)
    rids = ["skew-%d" % i for i in range(6)]
    futs = index.submit_batch(data[:6, :], 5, rids=rids)
    gather_futures(futs, 5)
    fams = {f.name: f for f in metrics.collect_families()}
    assert "scheduler.shard_iters" in fams
    shards = {lbl["shard"] for lbl, _v in
              fams["scheduler.shard_iters"].samples}
    assert shards == {"0", "1"}
    timeline.sample_now(now=1.0)
    keys = [k for k in timeline.series_names()
            if k.startswith("scheduler.shard_iters{")]
    assert len(keys) == 2
    st = flightrec.query_stats("skew-0")
    assert st is not None and "shard_imbalance" in st
    assert st["slow_shard"] in (0, 1)
    assert metrics.gauge_value("scheduler.shard_skew") >= 0.0


# ---------------------------------------------------------------------------
# scheduler iter_cost1 regression (the gflops= root cause)
# ---------------------------------------------------------------------------

def test_slot_pool_iter_cost1_resolves():
    """Regression: _SlotPool.iter_cost1 referenced a nonexistent
    attribute and the swallowed AttributeError silently disabled the
    slow-query log's gflops= attribution (ISSUE 15 satellite)."""
    from sptag_tpu.algo.scheduler import _SlotPool
    from sptag_tpu.utils.costmodel import CostEstimate

    class _Engine:
        def walk_iter_cost(self, rows, B, L):
            return CostEstimate("beam.walk_iter", 100.0 * rows,
                                50.0 * rows)

    pool = _SlotPool((5, 32, 16, 3, None, 0), _Engine(),
                     seg_iters=4, slots=64)
    est = pool.iter_cost1()
    assert est is not None
    assert est.flops == pytest.approx(100.0)
    assert est.hbm_bytes == pytest.approx(50.0)


# ---------------------------------------------------------------------------
# off-parity: everything default == byte-identical + zero work
# ---------------------------------------------------------------------------

def test_timeline_off_parity_serve_bytes_and_no_threads():
    """With every ISSUE 15 knob at its default the serve path produces
    byte-identical wire responses, the timeline counters read zero and
    no sampler/prober thread exists (the ci_check.sh standalone parity
    pass)."""
    idx, data = _flat_index(n=50, d=8)
    ctx = ServiceContext(ServiceSettings(default_max_result=5))
    ctx.add_index("main", idx)
    server = SearchServer(ctx, batch_window_ms=1.0)
    t = ServerThread(server)
    t.start()
    host, port = t.wait_ready(60)
    try:
        assert not timeline.enabled()
        assert server._slo is None and server._canary is None
        qtext = "|".join(str(x) for x in data[7])
        expected_result = SearchExecutor(ctx).execute(qtext)
        expected_result.request_id = ""
        expected_body = expected_result.pack()
        expected = wire.PacketHeader(
            wire.PacketType.SearchResponse, wire.PacketProcessStatus.Ok,
            len(expected_body), 1, 77).pack() + expected_body

        body = wire.RemoteQuery(qtext).pack()
        s = socket.create_connection((host, port), timeout=10)
        s.sendall(wire.PacketHeader(
            wire.PacketType.SearchRequest, wire.PacketProcessStatus.Ok,
            len(body), 0, 77).pack() + body)
        s.settimeout(10)
        got = b""
        while len(got) < len(expected):
            chunk = s.recv(65536)
            if not chunk:
                break
            got += chunk
        s.close()
        assert got == expected
        assert timeline.counters() == {
            "enabled": 0, "samples": 0, "recorded": 0, "series": 0,
            "series_dropped": 0, "listener_errors": 0}
        names = {th.name for th in threading.enumerate()}
        assert "timeline-sampler" not in names
        assert "canary-prober" not in names
        # record() with the store off is a no-op flag test
        timeline.record("t.off", 1.0)
        assert timeline.series_names() == []
    finally:
        t.stop()
