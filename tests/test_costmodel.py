"""Cost ledger (ISSUE 6): registry semantics and THE acceptance
cross-check — registered FLOPs/bytes for the flat, dense and
beam-segment kernels agree with XLA's own `Compiled.cost_analysis()`
within ±15% on the CPU backend (tools/ci_check.sh runs the crosscheck
subset standalone)."""

import jax.numpy as jnp
import numpy as np
import pytest

from sptag_tpu.core.types import DistCalcMethod
from sptag_tpu.utils import costmodel, metrics

TOL = costmodel.DEFAULT_TOLERANCE


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_registry_has_every_serving_family():
    """Importing the kernel modules registers the families the roofline
    rows and GL605 depend on."""
    import sptag_tpu.algo.dense  # noqa: F401
    import sptag_tpu.algo.engine  # noqa: F401
    import sptag_tpu.algo.flat  # noqa: F401
    import sptag_tpu.ops.distance  # noqa: F401

    fams = set(costmodel.families())
    for want in ("flat.scan", "flat.sketch_scan", "dense.scan",
                 "dense.grouped", "beam.seed", "beam.segment",
                 "beam.finalize", "beam.walk", "distance.batch_topk",
                 "distance.row_sqnorms"):
        assert want in fams, (want, fams)
    names = set(costmodel.registered_kernel_names())
    assert "_flat_search_kernel" in names
    assert "_beam_segment_kernel" in names


def test_estimate_unknown_family_raises():
    with pytest.raises(KeyError):
        costmodel.estimate("no.such.family", Q=1)


def test_estimate_returns_positive_physics():
    import sptag_tpu.algo.flat  # noqa: F401

    est = costmodel.estimate("flat.scan", Q=32, N=1024, D=64, k=10)
    assert est.flops > 2 * 32 * 1024 * 64 * 0.9
    assert est.hbm_bytes > 1024 * 64 * 4          # at least the corpus
    assert est.intensity > 0


def test_crosscheck_mismatch_increments_counter(caplog):
    """A formula that drifts from its kernel is VISIBLE: the counter
    bumps and the delta is logged."""
    import jax

    @jax.jit
    def tiny(x):
        return x @ x

    costmodel.register("test.bad_formula", tiny,
                       lambda **s: (1.0, 1.0))   # absurdly wrong
    compiled = tiny.lower(jnp.ones((64, 64))).compile()
    before = metrics.counter_value("costmodel.xla_mismatch")
    rel = costmodel.crosscheck("test.bad_formula", compiled)
    assert metrics.counter_value("costmodel.xla_mismatch") == before + 1
    assert rel["flops_rel"] < -0.9                # ledger far below XLA


# ---------------------------------------------------------------------------
# acceptance: ledger vs cost_analysis within ±15% (CPU backend)
# ---------------------------------------------------------------------------

def _assert_close(family, compiled, **shape):
    before = metrics.counter_value("costmodel.xla_mismatch")
    rel = costmodel.crosscheck(family, compiled, **shape)
    assert abs(rel["flops_rel"]) <= TOL, (family, shape, rel)
    assert abs(rel["bytes_rel"]) <= TOL, (family, shape, rel)
    assert metrics.counter_value("costmodel.xla_mismatch") == before


@pytest.mark.parametrize("Q,N,D,k", [(32, 1024, 64, 10), (8, 512, 32, 5)])
def test_crosscheck_flat_scan(Q, N, D, k):
    from sptag_tpu.algo.flat import _flat_search_kernel

    data = jnp.zeros((N, D))
    compiled = _flat_search_kernel.lower(
        data, jnp.zeros((N,)), jnp.zeros((N,), bool), jnp.zeros((Q, D)),
        k, int(DistCalcMethod.L2), 1, False).compile()
    _assert_close("flat.scan", compiled, Q=Q, N=N, D=D, k=k)


@pytest.mark.parametrize("Q,C,P,D,nprobe,k", [(32, 64, 128, 64, 4, 10)])
def test_crosscheck_dense_scan(Q, C, P, D, nprobe, k):
    from sptag_tpu.algo.dense import _dense_search_kernel

    compiled = _dense_search_kernel.lower(
        jnp.zeros((C, P, D)), jnp.zeros((C, P), jnp.int32),
        jnp.zeros((C, P)), jnp.zeros((C, D)), jnp.zeros((C,)),
        jnp.zeros((C * P,), bool), jnp.zeros((Q, D)),
        k, nprobe, int(DistCalcMethod.L2), 1, False, False,
        False).compile()
    _assert_close("dense.scan", compiled, Q=Q, C=C, P=P, D=D,
                  nprobe=nprobe, k=k)


@pytest.mark.parametrize("Q,C,P,D,nprobe,U,G,k",
                         [(32, 64, 128, 64, 4, 8, 8, 10),
                          (64, 32, 64, 32, 2, 4, 4, 5)])
def test_crosscheck_dense_grouped(Q, C, P, D, nprobe, U, G, k):
    """ISSUE 13 satellite: the grouped-dense family, never crosschecked
    before, holds the same ±15% bar at two shapes."""
    from sptag_tpu.algo.dense import _dense_search_grouped_kernel

    compiled = _dense_search_grouped_kernel.lower(
        jnp.zeros((C, P, D)), jnp.zeros((C, P), jnp.int32),
        jnp.zeros((C, P)), jnp.zeros((C, D)), jnp.zeros((C,)),
        jnp.zeros((C * P,), bool), jnp.zeros((Q, D)),
        jnp.int32(Q), k, nprobe, U, G, int(DistCalcMethod.L2), 1,
        False, False, False, 0).compile()
    _assert_close("dense.grouped", compiled, Q=Q, C=C, P=P, D=D,
                  nprobe=nprobe, U=U, G=G, k=k)


@pytest.mark.parametrize("Q,L,B,N,D,m,S",
                         [(8, 64, 16, 2048, 64, 32, 4),
                          (32, 128, 32, 4096, 128, 32, 8)])
def test_crosscheck_beam_segment(Q, L, B, N, D, m, S):
    """The walk body follows the count-body-once convention: the
    registered beam.segment cost is ONE iteration regardless of S (the
    two S values here compile different programs, same cost)."""
    from sptag_tpu.algo.engine import _beam_segment_kernel, _num_words

    W = _num_words(N)
    compiled = _beam_segment_kernel.lower(
        jnp.zeros((N, D)), jnp.zeros((N,)),
        jnp.zeros((N, m), jnp.int32), jnp.zeros((Q, D)),
        jnp.zeros((Q,), jnp.int32), jnp.zeros((Q, L), jnp.int32),
        jnp.zeros((Q, L)), jnp.zeros((Q, L + 1), bool),
        jnp.zeros((Q, W), jnp.int32), jnp.zeros((Q,), jnp.int32),
        jnp.zeros((Q,), jnp.int32), jnp.zeros((Q,), jnp.int32),
        10, L, B, S, int(DistCalcMethod.L2), 1, 3, 0,
        None, None, None, None, None).compile()
    _assert_close("beam.segment", compiled, Q=Q, X=B * m, D=D, W=W)


@pytest.mark.parametrize("Q,L,B,N,D,m,S",
                         [(8, 64, 16, 2048, 64, 32, 4),
                          (32, 128, 32, 4096, 128, 32, 8),
                          (16, 320, 64, 16384, 128, 32, 4)])
def test_crosscheck_beam_segment_binned(Q, L, B, N, D, m, S):
    """ISSUE 13: the BINNED walk body's recalibrated formula
    (WALK_BINNED_* constants + the explicit corpus gather-operand term)
    holds ±15% at three shapes, including the bench's (L=320, B=64)."""
    from sptag_tpu.algo.engine import _beam_segment_kernel, _num_words
    from sptag_tpu.ops import topk_bins

    W = _num_words(N)
    # the PRODUCTION bin rule (walk_merge_bins' pow2ceil(2L)), not an
    # arbitrary count — the crosscheck must pin the shipped configuration
    mb = topk_bins.walk_merge_bins("on", L, L + B * m)
    assert mb == topk_bins.pow2ceil(2 * L)
    compiled = _beam_segment_kernel.lower(
        jnp.zeros((N, D)), jnp.zeros((N,)),
        jnp.zeros((N, m), jnp.int32), jnp.zeros((Q, D)),
        jnp.zeros((Q,), jnp.int32), jnp.zeros((Q, L), jnp.int32),
        jnp.zeros((Q, L)), jnp.zeros((Q, L + 1), bool),
        jnp.zeros((Q, W), jnp.int32), jnp.zeros((Q,), jnp.int32),
        jnp.zeros((Q,), jnp.int32), jnp.zeros((Q,), jnp.int32),
        10, L, B, S, int(DistCalcMethod.L2), 1, 3, 0,
        None, None, None, None, None, mb).compile()
    _assert_close("beam.segment", compiled, Q=Q, X=B * m, D=D, W=W,
                  merge_bins=mb, L=L, N=N)


@pytest.mark.parametrize("Q,N,D,k,rt", [(32, 4096, 64, 10, 0.9)])
def test_crosscheck_flat_scan_binned(Q, N, D, k, rt):
    """The binned FLAT select's formula (one fewer full (Q, N) traversal
    + the shortlist select term) holds the same bar."""
    from sptag_tpu.algo.flat import _flat_search_kernel
    from sptag_tpu.ops import topk_bins

    bins = topk_bins.bins_for(k, N, rt)
    compiled = _flat_search_kernel.lower(
        jnp.zeros((N, D)), jnp.zeros((N,)), jnp.zeros((N,), bool),
        jnp.zeros((Q, D)), k, int(DistCalcMethod.L2), 1, False, rt,
        bins).compile()
    _assert_close("flat.scan", compiled, Q=Q, N=N, D=D, k=k,
                  binned_bins=bins)


def test_walk_iter_cost_matches_segment_family():
    """The engine helper the gauges and slow-query attribution consume
    is exactly the registered beam.segment formula at the engine's own
    static shapes."""
    import sptag_tpu.algo.engine as E

    rng = np.random.default_rng(0)
    data = rng.standard_normal((200, 16)).astype(np.float32)
    graph = rng.integers(0, 200, (200, 8)).astype(np.int32)
    eng = E.GraphSearchEngine(data, graph, np.arange(16, dtype=np.int32),
                              None, DistCalcMethod.L2, 1,
                              score_dtype="f32")
    est = eng.walk_iter_cost(4, 8)
    ref = costmodel.estimate("beam.segment", Q=4, X=8 * 8, D=16,
                             W=E._num_words(200), score_itemsize=4)
    assert est.flops == ref.flops and est.hbm_bytes == ref.hbm_bytes


def test_xla_cost_tolerates_dict_and_list_forms():
    class FakeDict:
        def cost_analysis(self):
            return {"flops": 5.0, "bytes accessed": 7.0}

    class FakeList:
        def cost_analysis(self):
            return [{"flops": 5.0, "bytes accessed": 7.0}]

    assert costmodel.xla_cost(FakeDict()) == (5.0, 7.0)
    assert costmodel.xla_cost(FakeList()) == (5.0, 7.0)
