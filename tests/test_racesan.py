"""Runtime race sanitizer (utils/locksan.py, ISSUE 12) + static/runtime
guard cross-check.

Key proofs:

* a PLANTED unguarded-write race (two threads interleaving writes with
  no common lock) is detected: ``racesan.races`` bumps, the record
  carries BOTH stacks, the log names both threads, strict mode raises
  `DataRaceError`;
* a lock-protected write hammer stays clean, and its observed lockset
  is exactly the protecting lock;
* the one-way ownership handoff (build on main, mutate on one worker
  forever after) is NOT a race — the Eraser transfer refinement;
* sampling is a deterministic per-thread 1-in-round(1/rate) gate;
* with RaceSanitizer off (the default) every tracked class is
  completely untouched (no ``__setattr__`` in the class dict), zero
  writes are recorded, and the serve tier's wire bytes are
  byte-identical to the reference layout (ci_check.sh parity pass);
* the static guard inference (tools/graftlint/guardedby.infer_guards)
  AGREES with the locksets a live BKT mutate-under-load workload
  actually held — the ISSUE 12 acceptance, mirroring how ISSUE 3
  cross-checked lockgraph vs locksan.
"""

import os
import socket
import sys
import threading

import numpy as np
import pytest

import sptag_tpu as sp
from sptag_tpu.serve import wire
from sptag_tpu.serve.aggregator import AggregatorContext
from sptag_tpu.serve.server import SearchServer
from sptag_tpu.serve.service import (SearchExecutor, ServiceContext,
                                     ServiceSettings)
from sptag_tpu.utils import locksan, metrics

from tests.test_serve import _ServerThread

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)


@pytest.fixture(autouse=True)
def _fresh_racesan():
    locksan.reset_racesan()
    yield
    locksan.reset_racesan()
    locksan.reset_config()
    locksan.reset_observations()


@locksan.race_track
class _Victim:
    """Tracked test class — registered once at module import, shimmed
    only while a test arms the sanitizer."""

    def __init__(self):
        self.guarded = 0


# ---------------------------------------------------------------------------
# detection semantics
# ---------------------------------------------------------------------------

@pytest.mark.racesan_ok
def test_planted_unguarded_race_detected_with_both_stacks(caplog):
    locksan.enable()
    locksan.enable_racesan()
    v = _Victim()
    a_wrote = threading.Event()
    b_wrote = threading.Event()

    def first_writer():
        v.racy = 1                      # virgin -> exclusive (thread A)
        a_wrote.set()
        assert b_wrote.wait(5)
        v.racy = 3                      # interleaves after B -> RACE

    def second_writer():
        assert a_wrote.wait(5)
        v.racy = 2                      # handoff transition: not checked
        b_wrote.set()

    before = metrics.counter_value("racesan.races")
    ta = threading.Thread(target=first_writer, name="racer-A")
    tb = threading.Thread(target=second_writer, name="racer-B")
    with caplog.at_level("ERROR", logger="sptag_tpu.utils.locksan"):
        ta.start()
        tb.start()
        ta.join(10)
        tb.join(10)
    assert locksan.race_count() == 1
    assert metrics.counter_value("racesan.races") == before + 1
    rec = locksan.races()[0]
    assert rec["class"] == "_Victim" and rec["attr"] == "racy"
    # BOTH stacks ride on the record: the previous conflicting write
    # and the one that closed the race
    assert "second_writer" in rec["prev_stack"]
    assert "first_writer" in rec["stack"]
    assert rec["prev_thread"] == "racer-B" and rec["thread"] == "racer-A"
    msgs = [r.getMessage() for r in caplog.records
            if "data race" in r.getMessage()]
    assert msgs and "previous write" in msgs[0] and \
        "this write" in msgs[0]


def test_lock_protected_hammer_stays_clean_and_lockset_observed():
    locksan.enable()
    locksan.enable_racesan()
    v = _Victim()
    lk = locksan.make_lock("VictimGuard")

    def hammer():
        for _ in range(200):
            with lk:
                v.guarded += 1

    ts = [threading.Thread(target=hammer) for _ in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    assert locksan.race_count() == 0
    obs = locksan.observed_locksets()
    rec = obs[("_Victim", "guarded")]
    assert len(rec["threads"]) >= 2
    assert rec["lockset"] == {"VictimGuard"}


@pytest.mark.racesan_ok
def test_strict_mode_raises_data_race_error():
    locksan.enable()
    locksan.enable_racesan(strict=True)
    v = _Victim()
    step1 = threading.Event()
    step2 = threading.Event()
    raised = []

    def a():
        v.racy = 1
        step1.set()
        assert step2.wait(5)
        try:
            v.racy = 3
        except locksan.DataRaceError as e:
            raised.append(e)

    def b():
        assert step1.wait(5)
        v.racy = 2
        step2.set()

    ta, tb = threading.Thread(target=a), threading.Thread(target=b)
    ta.start(); tb.start()
    ta.join(10); tb.join(10)
    assert raised and "racy" in str(raised[0])
    # the write itself landed — the raise is the report, not a rollback
    assert v.racy == 3


def test_ownership_handoff_is_not_a_race():
    """Built on main, mutated by exactly one worker forever after: the
    spawn edge synchronizes the transfer and no race fires even though
    neither side holds a lock."""
    locksan.enable()
    locksan.enable_racesan()
    v = _Victim()
    v.state = "built"                     # main thread

    def worker():
        for i in range(50):
            v.state = i                   # sole writer from now on

    t = threading.Thread(target=worker)
    t.start()
    t.join(10)
    assert locksan.race_count() == 0


def test_sampling_rate_is_deterministic_per_thread():
    locksan.enable()
    locksan.enable_racesan(sample_rate=0.25)      # record every 4th
    v = _Victim()
    before = locksan.racesan_counters()["writes_recorded"]

    def writer():                          # fresh thread: tick starts 0
        for i in range(16):
            v.ticked = i

    t = threading.Thread(target=writer)
    t.start()
    t.join(10)
    assert locksan.racesan_counters()["writes_recorded"] == before + 4
    # rate 0 records nothing
    locksan.reset_racesan()
    locksan.enable_racesan(sample_rate=0.0)
    t = threading.Thread(target=writer)
    t.start()
    t.join(10)
    assert locksan.racesan_counters()["writes_recorded"] == 0


@pytest.mark.skipif(bool(os.environ.get("SPTAG_RACESAN")),
                    reason="install-state assertions need the default "
                           "(unarmed) environment")
def test_enable_disable_install_semantics():
    assert "__setattr__" not in _Victim.__dict__
    locksan.enable_racesan()
    assert "__setattr__" in _Victim.__dict__

    # classes registered AFTER arming are shimmed on the spot
    @locksan.race_track
    class Late:
        pass
    assert "__setattr__" in Late.__dict__
    locksan.disable_racesan()
    assert "__setattr__" not in _Victim.__dict__
    assert "__setattr__" not in Late.__dict__
    # a subclass of a tracked class inherits the shim, and instance
    # behavior is unchanged either way
    locksan.enable_racesan()

    class Sub(_Victim):
        pass
    s = Sub()
    s.extra = 1
    assert s.extra == 1
    assert locksan.racesan_counters()["writes_recorded"] >= 1


def test_ini_knobs_arm_both_tiers(tmp_path):
    ini = tmp_path / "svc.ini"
    ini.write_text(
        "[Service]\n"
        "RaceSanitizer=1\n"
        "RaceSanSampleRate=0.25\n")
    ctx = ServiceContext.from_ini(str(ini))
    assert ctx.settings.race_sanitizer
    assert ctx.settings.racesan_sample_rate == 0.25
    assert locksan.racesan_enabled()
    assert "__setattr__" in _Victim.__dict__
    locksan.reset_racesan()
    agg_ini = tmp_path / "agg.ini"
    agg_ini.write_text("[Service]\nRaceSanitizer=strict\n")
    actx = AggregatorContext.from_ini(str(agg_ini))
    assert actx.race_sanitizer
    assert locksan.racesan_enabled() and locksan.racesan_strict()
    # defaults stay off
    locksan.reset_racesan()
    assert ServiceSettings().race_sanitizer is False
    assert AggregatorContext().race_sanitizer is False


# ---------------------------------------------------------------------------
# off-path: zero work, byte parity
# ---------------------------------------------------------------------------

@pytest.mark.skipif(bool(os.environ.get("SPTAG_RACESAN")),
                    reason="off-path parity needs the default (unarmed) "
                           "environment")
def test_racesan_off_parity_serve_bytes_and_untouched_classes():
    """With RaceSanitizer at its default (off), every registered hot
    class is completely untouched — not even a flag test on the write
    path — zero writes are recorded, and the serve tier's wire bytes
    are byte-identical to the reference layout (the ci_check.sh
    standalone parity pass)."""
    from sptag_tpu.algo.scheduler import BeamSlotScheduler
    from sptag_tpu.core.delta import DeltaShard
    from sptag_tpu.core.index import VectorIndex
    from sptag_tpu.parallel.sharded import ServingAdapter
    from sptag_tpu.serve.admission import AdmissionController
    from sptag_tpu.serve.aggregator import AggregatorService

    assert not locksan.racesan_enabled()
    for cls in (VectorIndex, BeamSlotScheduler, DeltaShard,
                ServingAdapter, AdmissionController, AggregatorService):
        assert "__setattr__" not in cls.__dict__, cls

    rng = np.random.default_rng(0)
    data = rng.standard_normal((50, 8)).astype(np.float32)
    index = sp.create_instance("FLAT", "Float")
    index.set_parameter("DistCalcMethod", "L2")
    index.build(data)
    ctx = ServiceContext(ServiceSettings(default_max_result=5))
    ctx.add_index("main", index)
    server = SearchServer(ctx, batch_window_ms=1.0)
    t = _ServerThread(server)
    t.start()
    host, port = t.wait_ready()
    try:
        qtext = "|".join(str(x) for x in data[7])
        expected_result = SearchExecutor(ctx).execute(qtext)
        expected_result.request_id = ""
        expected_body = expected_result.pack()
        expected = wire.PacketHeader(
            wire.PacketType.SearchResponse, wire.PacketProcessStatus.Ok,
            len(expected_body), 1, 77).pack() + expected_body
        body = wire.RemoteQuery(qtext).pack()
        s = socket.create_connection((host, port), timeout=10)
        s.sendall(wire.PacketHeader(
            wire.PacketType.SearchRequest, wire.PacketProcessStatus.Ok,
            len(body), 0, 77).pack() + body)
        s.settimeout(10)
        got = b""
        while len(got) < len(expected):
            chunk = s.recv(65536)
            if not chunk:
                break
            got += chunk
        s.close()
        assert got == expected
        c = locksan.racesan_counters()
        assert c["enabled"] == 0 and c["writes_recorded"] == 0 and \
            c["races"] == 0
    finally:
        t.stop()


# ---------------------------------------------------------------------------
# static/runtime guard cross-check (the ISSUE 12 acceptance)
# ---------------------------------------------------------------------------

def _suffix_match(canonical: str, runtime_name: str) -> bool:
    return canonical == runtime_name or \
        canonical.endswith("." + runtime_name)


def test_static_guard_inference_agrees_with_runtime_locksets(tmp_path):
    """Drive a BKT mutate-under-load workload (delta-shard adds + a
    background refine/swap + concurrent searchers) with the race
    sanitizer armed, then check BOTH directions of the contract:

    * the workload is race-free (racesan.races == 0 — the armed-smoke
      acceptance);
    * every attribute the sanitizer saw written by MULTIPLE threads
      under a surviving lockset has a statically inferred guard that
      names one of those locks — i.e. guardedby.infer_guards() and the
      runtime agree on WHO protects the index's shared state.
    """
    from tools.graftlint import guardedby
    from tools.graftlint.core import Project

    locksan.enable(strict=True)
    locksan.enable_racesan()
    locksan.reset_observations()

    rng = np.random.default_rng(11)
    data = rng.standard_normal((256, 16)).astype(np.float32)
    index = sp.create_instance("BKT", "Float")
    for name, value in [("DistCalcMethod", "L2"), ("BKTKmeansK", "8"),
                        ("TPTNumber", "2"), ("TPTLeafSize", "64"),
                        ("NeighborhoodSize", "8"), ("CEF", "32"),
                        ("MaxCheck", "256"), ("RefineIterations", "1"),
                        ("Samples", "64"), ("AddCountForRebuild", "32"),
                        ("DeltaShardCapacity", "128"),
                        ("AutoRefineThreshold", "64")]:
        index.set_parameter(name, value)
    assert index.build(data) == sp.ErrorCode.Success

    stop = threading.Event()
    errors = []

    def searcher():
        q = rng.standard_normal((4, 16)).astype(np.float32)
        while not stop.is_set():
            try:
                index.search_batch(q, 5)
            except Exception as e:            # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=searcher, name=f"xchk-s{i}")
               for i in range(2)]
    for t in threads:
        t.start()
    try:
        for i in range(0, 128, 32):
            extra = rng.standard_normal((32, 16)).astype(np.float32)
            assert index.add(extra) == sp.ErrorCode.Success
        index.wait_for_rebuild(30)
    finally:
        stop.set()
        for t in threads:
            t.join(10)
    index.close()
    assert not errors, errors
    assert locksan.race_count() == 0, locksan.races()

    observed = locksan.observed_locksets()
    multi = {k: v for k, v in observed.items()
             if len(v["threads"]) >= 2 and v["lockset"]}
    # the workload really produced cross-thread guarded writes
    assert any("VectorIndex._lock" in v["lockset"]
               for v in multi.values()), observed

    guards = guardedby.infer_guards(
        Project.from_tree(os.path.join(REPO, "sptag_tpu")))
    by_attr = {}
    for (dotted_cls, attr), g in guards.items():
        by_attr.setdefault(attr, []).append((dotted_cls, g))

    checked = 0
    for (cls, attr), rec in multi.items():
        cands = by_attr.get(attr)
        if not cands:
            continue                   # attr invisible statically
        agree = any(
            any(_suffix_match(c, name)
                for c in g for name in rec["lockset"])
            for _dc, g in cands if g)
        assert agree, (
            f"runtime saw `{cls}.{attr}` consistently written under "
            f"{sorted(rec['lockset'])} but the static inference has "
            f"guards {cands}")
        checked += 1
    assert checked >= 1, (multi, "nothing cross-checked")
