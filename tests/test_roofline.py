"""Capability registry + roofline wiring (ISSUE 6): table lookup, the
disk-cached micro-probe, roofline_row math, the perf_report renderer,
engine gauges, and THE acceptance e2e — an aggregator over two shard
servers whose /metrics exposes engine.roofline_pct_peak and
memory.device_bytes, /debug/memory answers, the slow-query log carries
per-query GFLOP/s, and serve wire bytes stay byte-identical with the
new knobs at their defaults."""

import json
import logging
import socket
import time

import numpy as np
import pytest

import sptag_tpu as sp
from sptag_tpu.serve import wire
from sptag_tpu.serve.aggregator import (AggregatorContext,
                                        AggregatorService, RemoteServer)
from sptag_tpu.serve.server import SearchServer
from sptag_tpu.serve.service import (SearchExecutor, ServiceContext,
                                     ServiceSettings)
from sptag_tpu.utils import metrics, roofline

from tests.test_serve import _ServerThread


def _http_get(port, path):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read().decode()
    conn.close()
    return resp.status, body


# ---------------------------------------------------------------------------
# capability registry
# ---------------------------------------------------------------------------

def test_tpu_table_lookup_known_generations():
    for kind, bf16, gbps in [("TPU v5 lite", 197e12, 819.0),
                             ("TPU v4", 275e12, 1228.0),
                             ("TPU v3", 123e12, 900.0)]:
        cap = roofline._table_lookup(kind, "tpu")
        assert cap is not None and cap.source == "table"
        assert cap.peak_flops_bf16 == bf16
        assert cap.peak_flops_f32 == bf16 / 4.0
        assert cap.hbm_gbps == gbps


def test_v5p_not_shadowed_by_v5e():
    cap = roofline._table_lookup("TPU v5p", "tpu")
    assert cap.peak_flops_bf16 == 459e12


def test_int8_peak_uses_doubled_path_where_it_exists():
    """v5e-class chips run int8 matmuls at 2x the bf16 rate — scoring
    int8 kernels against the bf16 peak would overstate %-of-peak ~2x."""
    v5e = roofline._table_lookup("TPU v5 lite", "tpu")
    assert v5e.peak_flops("int8") == 2 * v5e.peak_flops_bf16
    v4 = roofline._table_lookup("TPU v4", "tpu")
    assert v4.peak_flops("int8") == v4.peak_flops_bf16
    # probe capabilities have no int8 measurement: fall back to bf16/f32
    probe = roofline.Capability("cpu", "cpu", 1e11, 1e11, 10.0, "probe")
    assert probe.peak_flops("int8") == 1e11


def test_unknown_kind_without_probe_has_no_peaks():
    assert roofline._table_lookup("cpu", "cpu") is None
    cap = roofline.Capability("cpu", "cpu", None, None, None, "none")
    assert cap.pct_of_peak(1e9, 1e9) is None
    assert cap.peak_flops("bf16") is None


def test_probe_outcome_is_disk_cached(tmp_path, monkeypatch):
    """The measured fallback runs device work ONCE per (kind, jax
    version): the second capability() resolves from disk (the PR-4
    probe-cache pattern)."""
    monkeypatch.setenv("SPTAG_TPU_ROOFLINE_CACHE", str(tmp_path))
    calls = []

    def fake_probe():
        calls.append(1)
        return {"peak_flops_f32": 1e11, "hbm_gbps": 10.0}

    monkeypatch.setattr(roofline, "_run_probe", fake_probe)
    roofline.reset()
    cap1 = roofline.capability(probe=True)
    roofline.reset()
    cap2 = roofline.capability(probe=True)
    roofline.reset()
    assert cap1.source == "probe" and cap2.source == "probe"
    assert cap1.peak_flops_f32 == 1e11 == cap2.peak_flops_f32
    assert len(calls) == 1                  # second hit came from disk
    # probe-flag DOWNGRADE live-applies: with probe=False the cached
    # probed capability must not leak through (RooflineProbe=0 turns
    # %-of-peak off on unknown kinds)
    cap3 = roofline.capability(probe=False)
    assert cap3.source == "none" and cap3.peak_flops_f32 is None
    roofline.reset()


def test_roofline_row_math_and_binding_resource():
    cap = roofline.Capability("x", "cpu", 1e12, 1e12, 100.0, "table")
    # compute-bound: high flops per byte
    row = roofline.roofline_row("f", 1e9, 1e3, qps=100.0, cap=cap)
    assert row["achieved_gflops"] == pytest.approx(100.0)
    assert row["pct_peak_flops"] == pytest.approx(10.0)
    assert row["bound"] == "compute"
    # bandwidth-bound: high bytes per flop
    row = roofline.roofline_row("f", 1e3, 1e9, qps=50.0, cap=cap)
    assert row["achieved_gbps"] == pytest.approx(50.0)
    assert row["pct_peak_hbm"] == pytest.approx(50.0)
    assert row["bound"] == "bandwidth"
    assert row["pct_peak"] == row["pct_peak_hbm"]


def test_perf_report_renders_bench_artifact():
    from sptag_tpu.tools import perf_report

    obj = {"platform": "cpu", "flat_qps": 1000.0, "value": 2000.0,
           "roofline": {
               "peaks": {"device_kind": "cpu", "source": "probe",
                         "peak_flops_f32": 1e11, "peak_flops_bf16": 1e11,
                         "hbm_gbps": 10.0},
               "rows": {"flat": {"family": "flat.scan",
                                 "flops_per_query": 10 ** 8,
                                 "hbm_bytes_per_query": 10 ** 6,
                                 "achieved_gflops": 100.0,
                                 "achieved_gbps": 1.0,
                                 "pct_peak_flops": 0.1,
                                 "pct_peak_hbm": 10.0,
                                 "bound": "bandwidth"}}}}
    lines = perf_report.report_from_bench(obj)
    text = "\n".join(lines)
    assert "| flat | flat.scan |" in text
    assert "bandwidth" in text
    assert "0.10 TFLOP/s" in text


def test_engine_resolves_capability_without_sampling():
    """The capability resolves at snapshot build even with device-time
    sampling OFF (its default), so the scheduler's slow-query pct_peak
    classification does not silently depend on the sampler."""
    from sptag_tpu.algo.engine import GraphSearchEngine
    from sptag_tpu.core.types import DistCalcMethod

    rng = np.random.default_rng(0)
    data = rng.standard_normal((64, 8)).astype(np.float32)
    graph = rng.integers(0, 64, (64, 4)).astype(np.int32)
    eng = GraphSearchEngine(data, graph, np.arange(8, dtype=np.int32),
                            None, DistCalcMethod.L2, 1, score_dtype="f32")
    assert eng.device_sample_rate == 0.0
    assert eng._capability is not None      # "none"-source at worst


def test_engine_gauges_published_on_sampled_segments():
    """FlightDeviceSampleRate=1 + RooflineProbe: every segment dispatch
    publishes achieved GFLOP/s / GB/s and %-of-peak gauges."""
    rng = np.random.default_rng(0)
    data = rng.standard_normal((120, 8)).astype(np.float32)
    idx = sp.create_instance("BKT", "Float")
    for p, v in [("DistCalcMethod", "L2"), ("BKTKmeansK", "4"),
                 ("TPTNumber", "2"), ("TPTLeafSize", "16"),
                 ("NeighborhoodSize", "8"), ("CEF", "32"),
                 ("RefineIterations", "0"), ("SearchMode", "beam"),
                 ("MaxCheck", "64"), ("BeamSegmentIters", "2"),
                 ("FlightDeviceSampleRate", "1"), ("RooflineProbe", "1")]:
        assert idx.set_parameter(p, v), p
    idx.build(data)
    idx.search_batch(data[:4], 3)
    assert metrics.gauge("engine.achieved_gflops").value > 0
    assert metrics.gauge("engine.achieved_gbps").value > 0
    # RooflineProbe=1 guarantees a capability on every platform (table
    # on TPU, measured probe here on CPU) -> the pct gauge exists
    assert metrics.gauge("engine.roofline_pct_peak").value > 0
    idx.close()


# ---------------------------------------------------------------------------
# acceptance e2e: aggregator + 2 shards
# ---------------------------------------------------------------------------

@pytest.fixture()
def roofline_serving(tmp_path):
    rng = np.random.default_rng(0)
    data = rng.standard_normal((120, 8)).astype(np.float32)
    idx = sp.create_instance("BKT", "Float")
    for p, v in [("DistCalcMethod", "L2"), ("BKTKmeansK", "4"),
                 ("TPTNumber", "2"), ("TPTLeafSize", "16"),
                 ("NeighborhoodSize", "8"), ("CEF", "32"),
                 ("RefineIterations", "0"), ("SearchMode", "beam"),
                 ("MaxCheck", "64"), ("BeamSegmentIters", "2"),
                 ("FlightDeviceSampleRate", "1"), ("RooflineProbe", "1"),
                 ("ContinuousBatching", "1")]:
        assert idx.set_parameter(p, v), p
    idx.build(data)
    idx.search_batch(data[:1], 3)
    yield idx, data
    idx.close()


def test_roofline_e2e_aggregator_two_shards(roofline_serving):
    """ISSUE 6 acceptance: scrape engine.roofline_pct_peak and
    memory.device_bytes from /metrics, fetch /debug/memory, and find the
    per-query GFLOP/s attribution in the slow-query log."""
    idx, data = roofline_serving
    ctx_a = ServiceContext(ServiceSettings(default_max_result=3))
    ctx_a.add_index("shard_a", idx)
    ctx_b = ServiceContext(ServiceSettings(default_max_result=3))
    ctx_b.add_index("shard_b", idx)
    srv_a = SearchServer(ctx_a, batch_window_ms=1.0, metrics_port=-1,
                         slow_query_threshold_ms=1e-6,
                         flight_recorder=True, flight_tier="server_a")
    srv_b = SearchServer(ctx_b, batch_window_ms=1.0,
                         slow_query_threshold_ms=1e-6,
                         flight_recorder=True, flight_tier="server_b")
    ta, tb = _ServerThread(srv_a), _ServerThread(srv_b)
    ta.start()
    tb.start()
    (ha, pa), (hb, pb) = ta.wait_ready(60), tb.wait_ready(60)
    agg_ctx = AggregatorContext(search_timeout_s=30.0)
    agg_ctx.servers = [RemoteServer(ha, pa), RemoteServer(hb, pb)]
    agg = AggregatorService(agg_ctx)
    tg = _ServerThread(agg)
    tg.start()
    hg, pg = tg.wait_ready(60)

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    shard_log = logging.getLogger("sptag_tpu.serve.server")
    capture = Capture()
    shard_log.addHandler(capture)
    rid = "e2e-roofline-007"
    try:
        from sptag_tpu.serve.client import AnnClient

        client = AnnClient(hg, pg, timeout_s=30.0)
        client.connect()
        qtext = ("$indexname:shard_a,shard_b $maxcheck:64 "
                 + "|".join(str(x) for x in data[5]))
        res = client.search(qtext, request_id=rid)
        assert res.status == wire.ResultStatus.Success
        client.close()

        # /metrics: the roofline gauges and the memory.device_bytes
        # component gauges, plus the flight health gauges (satellite:
        # they were counters()-only before)
        deadline = time.time() + 10
        text = ""
        while time.time() < deadline:
            status, text = _http_get(srv_a._metrics_http.port, "/metrics")
            assert status == 200
            if "sptag_tpu_engine_roofline_pct_peak" in text:
                break
            time.sleep(0.05)
        assert "sptag_tpu_engine_roofline_pct_peak" in text
        assert "sptag_tpu_engine_achieved_gflops" in text
        assert 'sptag_tpu_memory_device_bytes{component="corpus"}' in text
        assert 'sptag_tpu_memory_device_bytes{component="graph"}' in text
        assert "sptag_tpu_flight_recorded" in text
        assert "sptag_tpu_flight_dump_ratelimited" in text

        # /debug/memory: the ledger snapshot with the live-arrays
        # cross-check, on BOTH tiers
        status, body = _http_get(srv_a._metrics_http.port, "/debug/memory")
        assert status == 200
        snap = json.loads(body)
        assert snap["components"].get("corpus", 0) > 0
        assert snap["ledger_device_bytes"] <= snap["live_arrays_bytes"]

        # slow-query log: per-query achieved GFLOP/s (+ %-of-peak via
        # the probe capability) classifies the slow query
        deadline = time.time() + 10
        while time.time() < deadline:
            if any(("rid=%s" % rid) in m and "gflops=" in m
                   for m in records):
                break
            time.sleep(0.05)
        hits = [m for m in records
                if ("rid=%s" % rid) in m and "gflops=" in m]
        assert hits, records
        assert any("pct_peak=" in m for m in hits), hits
    finally:
        shard_log.removeHandler(capture)
        tg.stop()
        ta.stop()
        tb.stop()


def test_serve_bytes_identical_with_new_knobs_at_defaults():
    """RooflineProbe / DeviceBytesLedger / the gauges never touch the
    wire path: with the knobs at their defaults the serve response is
    byte-identical to the reference layout (the same golden-bytes
    construction as the flight off-parity gate)."""
    rng = np.random.default_rng(0)
    data = rng.standard_normal((50, 8)).astype(np.float32)
    index = sp.create_instance("FLAT", "Float")
    index.set_parameter("DistCalcMethod", "L2")
    assert index.get_parameter("RooflineProbe") == "0"
    assert index.get_parameter("DeviceBytesLedger") == "1"
    index.build(data)
    ctx = ServiceContext(ServiceSettings(default_max_result=5))
    ctx.add_index("main", index)
    server = SearchServer(ctx, batch_window_ms=1.0)
    t = _ServerThread(server)
    t.start()
    host, port = t.wait_ready(60)
    try:
        qtext = "|".join(str(x) for x in data[7])
        expected_result = SearchExecutor(ctx).execute(qtext)
        expected_result.request_id = ""
        expected_body = expected_result.pack()
        expected = wire.PacketHeader(
            wire.PacketType.SearchResponse, wire.PacketProcessStatus.Ok,
            len(expected_body), 1, 99).pack() + expected_body
        body = wire.RemoteQuery(qtext).pack()
        s = socket.create_connection((host, port), timeout=10)
        s.sendall(wire.PacketHeader(
            wire.PacketType.SearchRequest, wire.PacketProcessStatus.Ok,
            len(body), 0, 99).pack() + body)
        s.settimeout(10)
        got = b""
        while len(got) < len(expected):
            chunk = s.recv(65536)
            if not chunk:
                break
            got += chunk
        s.close()
        assert got == expected
    finally:
        t.stop()
