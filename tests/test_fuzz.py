"""Property-based fuzzing of the attacker-facing decoders (hypothesis).

The reference ships no tests for its wire stack at all (SURVEY.md §4);
round-3 hardening added hand-written malformed-packet tests — these
properties generalize them:

* pack→unpack round-trips hold for ARBITRARY well-formed values;
* unpack of ARBITRARY bytes never raises past its documented contract
  (None for body decoders, ValueError for the fixed-size header) — a
  hostile peer can produce any byte string, and one crash in the decode
  path would kill a server connection task;
* the query-line parser never raises on arbitrary text, and its vector
  extraction never raises on arbitrary base64-ish payloads (the text
  protocol is typed by external clients).
"""

import base64

import numpy as np
import pytest

hypothesis = pytest.importorskip("hypothesis")
from hypothesis import given, settings           # noqa: E402
from hypothesis import strategies as st          # noqa: E402

import sptag_tpu as sp
from sptag_tpu.serve import wire
from sptag_tpu.serve.protocol import parse_query

# distances that survive the f32 wire format exactly
_f32 = st.floats(width=32, allow_nan=False, allow_infinity=False)
_name = st.text(
    st.characters(codec="utf-8", exclude_categories=("Cs",)), max_size=32)


@st.composite
def _index_results(draw):
    n = draw(st.integers(0, 8))
    ids = draw(st.lists(st.integers(-1, 2**31 - 1), min_size=n, max_size=n))
    dists = draw(st.lists(_f32, min_size=n, max_size=n))
    metas = draw(st.one_of(
        st.none(),
        st.lists(st.binary(max_size=64), min_size=n, max_size=n)))
    return wire.IndexSearchResult(draw(_name), ids, dists, metas)


@given(st.lists(_index_results(), max_size=4),
       st.sampled_from(list(wire.ResultStatus)))
@settings(max_examples=200, deadline=None)
def test_remote_search_result_roundtrip_property(results, status):
    r = wire.RemoteSearchResult(status, results)
    r2 = wire.RemoteSearchResult.unpack(r.pack())
    assert r2 is not None
    assert r2.status == status
    assert len(r2.results) == len(results)
    for a, b in zip(results, r2.results):
        assert (a.index_name, a.ids, a.metas) == \
            (b.index_name, b.ids, b.metas)
        np.testing.assert_array_equal(
            np.asarray(a.dists, np.float32), np.asarray(b.dists, np.float32))


@given(st.text(max_size=200))
@settings(max_examples=200, deadline=None)
def test_remote_query_roundtrip_property(text):
    q2 = wire.RemoteQuery.unpack(wire.RemoteQuery(text).pack())
    assert q2 is not None and q2.query == text


@given(st.binary(max_size=300))
@settings(max_examples=300, deadline=None)
def test_unpack_arbitrary_bytes_never_raises(buf):
    # body decoders are total: a value or None, never an exception
    wire.RemoteQuery.unpack(buf)
    wire.RemoteSearchResult.unpack(buf)
    if len(buf) >= wire.HEADER_SIZE:
        wire.PacketHeader.unpack(buf[:wire.HEADER_SIZE])


@given(st.binary(max_size=200), st.integers(0, 199))
@settings(max_examples=200, deadline=None)
def test_truncated_packets_are_rejected_not_corrupted(raw, cut):
    """A well-formed packet cut short must decode to None — never to a
    'valid' object with silently truncated strings (read_string raises
    past end-of-buffer; the decoders translate that to None)."""
    full = wire.RemoteSearchResult(wire.ResultStatus.Success, [
        wire.IndexSearchResult("idx", [1, 2], [0.5, 1.5],
                               [raw, b"second-meta-payload"])]).pack()
    cut = min(cut, len(full) - 1)
    # this layout declares one result list up front, so EVERY proper
    # prefix is incomplete: decode must reject, never deliver shortened
    # strings as valid data
    assert wire.RemoteSearchResult.unpack(full[:cut]) is None


@given(st.text(max_size=300))
@settings(max_examples=300, deadline=None)
def test_parse_query_never_raises(text):
    p = parse_query(text)
    # option accessors are total too (typos degrade, never crash)
    p.index_names, p.data_type, p.extract_metadata
    p.result_num, p.max_check, p.search_mode
    p.extract_vector(sp.VectorValueType.Float)


@given(st.binary(max_size=120))
@settings(max_examples=200, deadline=None)
def test_extract_vector_base64_total(raw):
    # a '#' token whose payload is valid base64 of arbitrary bytes: either
    # a clean float vector or None — never an exception, never a partial
    # element (byte length must divide the dtype size)
    token = "#" + base64.b64encode(raw).decode()
    v = parse_query(token).extract_vector(sp.VectorValueType.Float)
    if v is not None:
        assert v.dtype == np.float32 and len(raw) % 4 == 0
