"""benchdiff regression sentinel (ISSUE 10): identity pass, doctored
regressions fail with a named metric, noise floors, direction
awareness, platform gating, missing-key/driver-envelope handling."""

import copy
import json
import os

import pytest

from tools import benchdiff

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
R05 = os.path.join(REPO, "BENCH_r05.json")


def _artifact(**overrides):
    base = {"schema_version": 1, "platform": "cpu", "value": 2000.0,
            "flat_qps": 800.0, "recall_at_10": 0.96,
            "p99_batch_ms": 700.0,
            "loadgen": {"qps_at_slo": 512.0, "p50_ms": 20.0,
                        "p99_ms": 100.0}}
    base.update(overrides)
    return base


def _write(tmp_path, name, obj):
    p = str(tmp_path / name)
    with open(p, "w") as f:
        json.dump(obj, f)
    return p


def test_identity_on_repo_artifact_passes(capsys):
    """THE acceptance command: the pinned repo artifact against itself
    exits 0."""
    assert benchdiff.main([R05, R05]) == 0
    out = capsys.readouterr().out
    assert "PASS" in out


def test_doctored_loadgen_p99_regression_fails(tmp_path, capsys):
    base = _artifact()
    cur = copy.deepcopy(base)
    cur["loadgen"]["p99_ms"] = 120.0           # -20% headroom
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main([bp, cp]) == 1
    out = capsys.readouterr().out
    assert "loadgen.p99_ms" in out and "REGRESSED" in out
    assert "FAIL" in out


def test_qps_drop_fails_and_names_metric(tmp_path, capsys):
    base = _artifact()
    cur = _artifact(value=1500.0)              # -25% headline QPS
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main([bp, cp]) == 1
    out = capsys.readouterr().out
    assert "value" in out and "REGRESSED" in out


def test_noise_floor_absorbs_small_absolute_wiggle(tmp_path):
    """A big RELATIVE change under the absolute min-delta floor passes:
    a 20->25 QPS beam-stage wiggle is noise, not regression."""
    base = _artifact(beam_qps=22.0)
    cur = _artifact(beam_qps=21.0)             # -4.5% rel, 1.0 abs < 2.0
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main([bp, cp]) == 0


def test_relative_threshold_absorbs_small_relative_wiggle(tmp_path):
    """A big ABSOLUTE change under the relative threshold passes: 15k
    -> 14.2k dense QPS is -5%, inside the 15% band."""
    base = _artifact(value=15000.0)
    cur = _artifact(value=14200.0)
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main([bp, cp]) == 0


def test_direction_awareness(tmp_path):
    """Latency UP regresses, QPS UP improves — never confused."""
    base = _artifact()
    faster = _artifact(value=3000.0, p99_batch_ms=300.0)
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", faster)
    assert benchdiff.main([bp, cp]) == 0
    slower_lat = _artifact(p99_batch_ms=1000.0)
    cp2 = _write(tmp_path, "c2.json", slower_lat)
    assert benchdiff.main([bp, cp2]) == 1


def test_recall_regression_fails_even_across_platforms(tmp_path, capsys):
    base = _artifact()
    cur = _artifact(platform="tpu", value=99999.0, recall_at_10=0.90)
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main([bp, cp]) == 1
    out = capsys.readouterr().out
    assert "platform mismatch" in out
    assert "recall_at_10" in out and "REGRESSED" in out


def test_platform_mismatch_skips_throughput(tmp_path, capsys):
    base = _artifact()
    cur = _artifact(platform="tpu", value=1.0, flat_qps=1.0)
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main([bp, cp]) == 0
    assert "platform mismatch" in capsys.readouterr().out


def test_missing_stage_keys_are_skipped_not_failed(tmp_path):
    base = _artifact()
    cur = _artifact()
    del cur["loadgen"]                 # stage budget-dropped this run
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main([bp, cp]) == 0


def test_driver_envelope_unwraps(tmp_path):
    base = {"n": 5, "rc": 0, "parsed": _artifact()}
    cur = {"n": 6, "rc": 0, "parsed": _artifact(value=100.0)}
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main([bp, cp]) == 1


def test_schema_version_mismatch_warns_but_diffs(tmp_path, capsys):
    base = _artifact(schema_version=0)
    cur = _artifact()
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main([bp, cp]) == 0
    assert "schema_version differs" in capsys.readouterr().out


def test_json_output_machine_readable(tmp_path, capsys):
    base = _artifact()
    cur = _artifact(value=1000.0)
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main(["--json", bp, cp]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["pass"] is False
    bad = [v for v in out["verdicts"] if v["status"] == "REGRESSED"]
    assert bad and bad[0]["metric"] == "value"


def test_load_errors_exit_2(tmp_path, capsys):
    missing = str(tmp_path / "nope.json")
    assert benchdiff.main([R05, missing]) == 2
    bad = _write(tmp_path, "bad.json", [1, 2, 3])
    assert benchdiff.main([R05, bad]) == 2


def test_resolve_dotted_paths():
    obj = {"a": {"b": {"c": 1.5}}, "x": True, "y": None, "z": "s"}
    assert benchdiff.resolve(obj, "a.b.c") == 1.5
    assert benchdiff.resolve(obj, "a.b.missing") is None
    assert benchdiff.resolve(obj, "x") is None       # bools excluded
    assert benchdiff.resolve(obj, "y") is None
    assert benchdiff.resolve(obj, "z") is None


@pytest.mark.parametrize("base,cur,direction,expect", [
    (100.0, 79.0, benchdiff.HIGHER, "REGRESSED"),   # -21%
    (100.0, 121.0, benchdiff.LOWER, "REGRESSED"),   # +21%
    (100.0, 121.0, benchdiff.HIGHER, "improved"),
    (100.0, 100.0, benchdiff.HIGHER, "ok"),
])
def test_judge_matrix(base, cur, direction, expect):
    m = benchdiff.Metric("m", direction, 0.20, 10.0)
    assert benchdiff.judge(m, base, cur).status == expect


def test_backend_compile_count_regression_fails(tmp_path, capsys):
    """ISSUE 16: per-stage `xla.backend_compile[bench.X]` span counts
    become direction-adjusted `<stage>.backend_compiles` lines — a
    stage minting MORE XLA programs than the baseline is a recompile
    regression even when QPS looks flat."""
    base = _artifact(trace={
        "xla.backend_compile[bench.sweep]": {"count": 4,
                                             "total_s": 2.0},
        "xla.backend_compile[bench.flat_quick]": {"count": 2,
                                                  "total_s": 0.5},
        "bench.sweep": {"count": 1, "total_s": 9.0}})
    cur = copy.deepcopy(base)
    cur["trace"]["xla.backend_compile[bench.sweep]"]["count"] = 12
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main([bp, cp]) == 1
    out = capsys.readouterr().out
    assert "bench.sweep.backend_compiles" in out and "REGRESSED" in out
    # the steady stage stays quiet; plain spans never synthesize a line
    assert "bench.flat_quick.backend_compiles REGRESSED" not in out


def test_backend_compile_counts_equal_pass_and_fewer_improve(tmp_path,
                                                             capsys):
    base = _artifact(trace={
        "xla.backend_compile[bench.sweep]": {"count": 8,
                                             "total_s": 2.0}})
    cur = copy.deepcopy(base)
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main([bp, cp]) == 0
    cur["trace"]["xla.backend_compile[bench.sweep]"]["count"] = 3
    cp = _write(tmp_path, "c2.json", cur)
    assert benchdiff.main([bp, cp]) == 0      # fewer compiles: improved
    # label present on only one side is skipped, not failed
    del cur["trace"]["xla.backend_compile[bench.sweep]"]
    cp = _write(tmp_path, "c3.json", cur)
    assert benchdiff.main([bp, cp]) == 0


def test_backend_compile_lines_are_platform_bound(tmp_path, capsys):
    base = _artifact(trace={
        "xla.backend_compile[bench.sweep]": {"count": 2,
                                             "total_s": 1.0}})
    cur = copy.deepcopy(base)
    cur["platform"] = "tpu"
    cur["trace"]["xla.backend_compile[bench.sweep]"]["count"] = 40
    bp = _write(tmp_path, "b.json", base)
    cp = _write(tmp_path, "c.json", cur)
    assert benchdiff.main([bp, cp]) == 0
    out = capsys.readouterr().out
    assert "platform mismatch" in out
