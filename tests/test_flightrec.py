"""Flight recorder (ISSUE 5): ring semantics, epoch-swap drain under
threads, Chrome-trace golden schema, scheduler/engine attribution, the
aggregator+2-shard end-to-end trace with flow arrows, the merge CLI,
and the FlightRecorder=off byte-parity / zero-work contract."""

import asyncio  # noqa: F401  (referenced via test_serve harness)
import json
import logging
import os
import socket
import threading
import time

import numpy as np
import pytest

import sptag_tpu as sp
from sptag_tpu.serve import wire
from sptag_tpu.serve.aggregator import (AggregatorContext,
                                        AggregatorService, RemoteServer)
from sptag_tpu.serve.server import SearchServer
from sptag_tpu.serve.service import (SearchExecutor, ServiceContext,
                                     ServiceSettings)
from sptag_tpu.tools import flight as flight_cli
from sptag_tpu.utils import flightrec, metrics

from tests.test_serve import _ServerThread


# ---------------------------------------------------------------------------
# ring semantics
# ---------------------------------------------------------------------------

def test_recorder_off_is_zero_work():
    """Off (the default): record() is a flag test — no events, no
    thread-local buffers minted, counters stay zero."""
    assert not flightrec.enabled()
    for _ in range(100):
        flightrec.record("server", "decode", "rid", dur_ns=5)
    with flightrec.span("server", "execute"):
        pass
    c = flightrec.counters()
    assert c == {"enabled": 0, "recorded": 0, "dropped": 0, "threads": 0,
                 "dump_errors": 0, "dump_ratelimited": 0}
    assert flightrec.collect() == []


def test_ring_overflow_drops_oldest_never_blocks():
    flightrec.configure(enabled=True, max_events=64)
    t0 = time.perf_counter()
    for i in range(1000):
        flightrec.record("t", "ev", payload={"seq": i})
    dt = time.perf_counter() - t0
    assert dt < 2.0                       # appends, not blocking waits
    evs = flightrec.collect()
    assert len(evs) == 64
    seqs = [e["payload"]["seq"] for e in evs]
    assert seqs == list(range(936, 1000))       # newest survive, in order
    c = flightrec.counters()
    assert c["recorded"] == 1000
    assert c["dropped"] == 936


def test_reset_restores_defaults():
    flightrec.configure(enabled=True, max_events=8, dump_dir="/tmp/x")
    flightrec.record("t", "ev")
    flightrec.note_query_stats("r", segments=1)
    flightrec.reset()
    assert not flightrec.enabled()
    assert flightrec.collect() == []
    assert flightrec.query_stats("r") is None
    c = flightrec.counters()
    assert c["recorded"] == 0 and c["threads"] == 0


def test_thread_hammer_epoch_swap_drain():
    """8 writers hammer the per-thread buffers while the main thread
    drains concurrently: nothing deadlocks, nothing is delivered twice,
    and accounting closes (delivered + dropped == recorded).

    Hardened (ISSUE 15 satellite): the drainer is DEADLINE-PACED on the
    stop event instead of spinning — a free-spinning drainer performs
    thousands of epoch swaps, and the documented race ("a writer racing
    the swap can strand at most ONE in-flight append per thread PER
    SWAP") then loses more than the old fixed `n_threads` slack allowed
    on a loaded suite host.  The loss bound below is the TRUE invariant
    — swaps-while-writing × writers — so the test cannot flake without
    a real recorder bug, and the pacing keeps the expected loss tiny."""
    n_threads, per_thread = 8, 2000
    flightrec.configure(enabled=True, max_events=4 * n_threads * per_thread)
    stop = threading.Event()
    drained = []
    drains = [0]

    def writer(t):
        for i in range(per_thread):
            flightrec.record("hammer", "ev", payload={"t": t, "i": i})

    def drainer():
        # wait() (deadline-based, stop-aware) rather than a bare
        # sleep/spin: stop takes effect immediately and each tick is
        # one epoch swap, counted for the loss bound
        while not stop.wait(0.002):
            drains[0] += 1
            drained.extend(flightrec.drain())
    dthread = threading.Thread(target=drainer)
    dthread.start()
    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    swaps_while_writing = drains[0] + 1   # +1: a tick mid-join race
    stop.set()
    dthread.join()
    # final drain AFTER every writer joined cannot strand anything
    drained.extend(flightrec.drain())
    c = flightrec.counters()
    assert c["recorded"] == n_threads * per_thread
    keys = [(e["payload"]["t"], e["payload"]["i"]) for e in drained]
    assert len(keys) == len(set(keys))          # exactly-once delivery
    # accounting never invents events, and the loss is bounded by the
    # race's real geometry: at most one in-flight append per thread per
    # concurrent swap
    assert len(keys) + c["dropped"] <= c["recorded"]
    assert len(keys) >= (c["recorded"] - c["dropped"]
                         - swaps_while_writing * n_threads)


# ---------------------------------------------------------------------------
# Chrome-trace export schema
# ---------------------------------------------------------------------------

def test_chrome_trace_export_golden_schema():
    flightrec.configure(enabled=True)
    flightrec.record("aggregator", "request", "rid-1", dur_ns=5000)
    flightrec.record("server_a", "execute", "rid-1", dur_ns=3000,
                     payload={"batch": 2})
    flightrec.record("server_a", "enqueue", "rid-1")          # instant
    flightrec.record("scheduler", "segment", dur_ns=1000)     # untagged
    trace = flightrec.export_chrome_trace()
    evs = trace["traceEvents"]
    # process metadata: one pid per tier, named
    meta = {e["args"]["name"]: e["pid"] for e in evs
            if e["ph"] == "M" and e["name"] == "process_name"}
    assert set(meta) == {"aggregator", "server_a", "scheduler"}
    assert len(set(meta.values())) == 3
    # complete spans carry ts + dur (microseconds); instants are ph=i
    spans = [e for e in evs if e["ph"] == "X"]
    assert {e["name"] for e in spans} == {"request", "execute", "segment"}
    for e in spans:
        assert e["dur"] > 0 and e["ts"] > 0 and "pid" in e and "tid" in e
    ex = next(e for e in spans if e["name"] == "execute")
    assert ex["args"]["rid"] == "rid-1" and ex["args"]["batch"] == 2
    assert any(e["ph"] == "i" and e["name"] == "enqueue" for e in evs)
    # flow arrows: s -> t -> f chain (3 rid-tagged events) sharing one id
    flows = [e for e in evs if e.get("cat") == "flight.flow"]
    assert {f["ph"] for f in flows} == {"s", "t", "f"}
    assert len({f["id"] for f in flows}) == 1
    # raw events ride along for the merge CLI
    assert len(trace["flightEvents"]) == 4
    assert trace["otherData"]["counters"]["recorded"] == 4
    json.dumps(trace)                     # the whole artifact serializes


def test_dump_dir_is_ringed(tmp_path):
    d = str(tmp_path / "dumps")
    flightrec.configure(enabled=True, dump_dir=d, dump_max_files=3,
                        dump_min_interval_s=0)
    flightrec.record("t", "ev")
    paths = [flightrec.dump_to_file("slow", "r%d" % i) for i in range(7)]
    assert all(p for p in paths)
    left = sorted(fn for fn in os.listdir(d) if fn.endswith(".json"))
    assert len(left) == 3
    assert os.path.basename(paths[-1]) in left      # newest kept
    with open(os.path.join(d, left[-1])) as f:
        data = json.load(f)
    assert data["otherData"]["reason"] == "slow"
    assert data["otherData"]["pid"] == os.getpid()


def test_dump_failure_is_counted_not_raised(tmp_path):
    """An unwritable dump dir must be visible (the serve tiers fire
    dumps from discarded executor futures): dump_to_file returns None,
    counts the failure, and never raises."""
    blocker = tmp_path / "not-a-dir"
    blocker.write_text("")
    flightrec.configure(enabled=True, dump_dir=str(blocker / "sub"))
    flightrec.record("t", "ev")
    assert flightrec.dump_to_file("slow", "r1") is None
    assert flightrec.counters()["dump_errors"] == 1


def test_merge_same_process_dumps_share_one_tier(tmp_path):
    """Two ringed dumps of ONE process (same otherData.pid) must not be
    split into two Perfetto processes; the same tier name from two
    DIFFERENT pids must."""
    flightrec.configure(enabled=True)
    flightrec.record("server", "request", "r1", dur_ns=100)
    flightrec.record("server", "request", "r2", dur_ns=100)
    raw = flightrec.collect()
    d1, d2 = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    with open(d1, "w") as f:          # two overlapping dumps, one pid
        json.dump({"flightEvents": raw[:1],
                   "otherData": {"pid": 1234}}, f)
    with open(d2, "w") as f:
        json.dump({"flightEvents": raw,
                   "otherData": {"pid": 1234}}, f)
    merged = flight_cli.merge_traces([d1, d2])
    tiers = {e["args"]["name"] for e in merged["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert tiers == {"server"}
    # same tier, different pid -> split with a source suffix
    with open(d2, "w") as f:
        json.dump({"flightEvents": raw[1:],
                   "otherData": {"pid": 5678}}, f)
    merged = flight_cli.merge_traces([d1, d2])
    tiers = {e["args"]["name"] for e in merged["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert tiers == {"server#pid1234", "server#pid5678"}


# ---------------------------------------------------------------------------
# params / ini parity
# ---------------------------------------------------------------------------

def test_flight_params_ini_parity(tmp_path):
    """The four FlightX parameters exist in the core registry (offline
    CLI passthrough) AND parse from [Service] on both serve tiers."""
    from sptag_tpu.core.params import BKTParams, KDTParams

    for cls in (BKTParams, KDTParams):
        p = cls()
        assert p.set_param("FlightRecorder", "1")
        assert p.set_param("FlightRecorderEvents", "4096")
        assert p.set_param("FlightDeviceSampleRate", "0.25")
        assert p.set_param("FlightDumpOnSlowQuery", "/tmp/fl")
        assert p.flight_recorder == 1
        assert p.flight_recorder_events == 4096
        assert p.flight_device_sample_rate == 0.25
        assert p.flight_dump_on_slow_query == "/tmp/fl"
        assert p.get_param("FlightDeviceSampleRate") == "0.25"
    ini = tmp_path / "svc.ini"
    ini.write_text("[Service]\nFlightRecorder=1\n"
                   "FlightRecorderEvents=2048\n"
                   "FlightDumpOnSlowQuery=/tmp/fdump\n")
    s = ServiceContext.from_ini(str(ini)).settings
    assert s.flight_recorder is True
    assert s.flight_recorder_events == 2048
    assert s.flight_dump_on_slow_query == "/tmp/fdump"
    a = AggregatorContext.from_ini(str(ini))
    assert a.flight_recorder is True
    assert a.flight_recorder_events == 2048
    assert a.flight_dump_on_slow_query == "/tmp/fdump"
    # defaults: everything off
    d = ServiceSettings()
    assert not d.flight_recorder and d.flight_dump_on_slow_query == ""


# ---------------------------------------------------------------------------
# scheduler + engine attribution (shared tiny beam index)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def beam_index():
    """One tiny continuous-batching BKT index shared by the scheduler
    and e2e tests (builds dominate suite cost — reuse warmed shapes)."""
    rng = np.random.default_rng(0)
    data = rng.standard_normal((120, 8)).astype(np.float32)
    idx = sp.create_instance("BKT", "Float")
    for p, v in [("DistCalcMethod", "L2"), ("BKTKmeansK", "4"),
                 ("TPTNumber", "2"), ("TPTLeafSize", "16"),
                 ("NeighborhoodSize", "8"), ("CEF", "32"),
                 ("RefineIterations", "0"), ("SearchMode", "beam"),
                 ("MaxCheck", "64"), ("BeamSegmentIters", "2"),
                 ("FlightDeviceSampleRate", "1"),
                 ("ContinuousBatching", "1")]:
        assert idx.set_parameter(p, v), p
    idx.build(data)
    idx.search_batch(data[:1], 3)         # warm the Q=1 bucket shapes
    yield idx, data
    idx.close()


def test_scheduler_flight_events_and_rid_stats(beam_index):
    idx, data = beam_index
    flightrec.configure(enabled=True)
    rids = ["rid-%02d" % i for i in range(4)]
    futs = idx.submit_batch(data[:4], 3, rids=rids)
    # ISSUE 5 small fix: by the time ANY future is readable, the retire
    # path has already published that batch's scheduler metrics — a
    # completion-triggered sample must not undercount its own query
    retired_at_cb = []
    futs[0].add_done_callback(
        lambda f: retired_at_cb.append(
            metrics.counter_value("scheduler.retired")))
    for f in futs:
        f.result()
    assert retired_at_cb and retired_at_cb[0] >= 1
    kinds = {(e["tier"], e["kind"]) for e in flightrec.collect()}
    for want in [("scheduler", "pending"), ("scheduler", "slot_assign"),
                 ("scheduler", "segment"), ("scheduler", "retire"),
                 ("engine", "segment_device")]:
        assert want in kinds, (want, kinds)
    # per-rid stats feed the slow-query log (and survive recorder off)
    st = flightrec.query_stats("rid-00")
    assert st is not None
    assert st["segments"] >= 1 and st["slot_wait_ms"] >= 0.0
    assert "refills" in st
    h = metrics.histogram_or_none("engine.segment_device_ns")
    assert h is not None and h.count >= 1 and h.max > 0


def test_flight_params_apply_on_warm_index(beam_index):
    """set_parameter on a WARM index must not be a silent no-op: the
    recorder knobs apply directly to the process recorder (both ways —
    enable AND disable), and the engine-baked sample rate invalidates
    the engine snapshot."""
    idx, data = beam_index
    assert not flightrec.enabled()
    assert idx.set_parameter("FlightRecorder", "1")
    assert flightrec.enabled()
    assert idx.set_parameter("FlightRecorder", "0")
    assert not flightrec.enabled()
    idx._get_engine()
    assert idx.set_parameter("FlightDeviceSampleRate", "0.5")
    assert idx._engine is None          # baked in -> snapshot invalidated
    assert idx.set_parameter("FlightDeviceSampleRate", "1")
    assert idx._get_engine().device_sample_rate == 1.0


def test_configure_resize_preserves_buffered_events():
    """Resizing the ring folds live thread buffers first — counters
    never go backwards and buffered events are not lost."""
    flightrec.configure(enabled=True)
    flightrec.record("t", "ev", payload={"seq": 1})
    flightrec.configure(max_events=4096)
    assert flightrec.counters()["recorded"] == 1
    assert [e["payload"]["seq"] for e in flightrec.collect()] == [1]


# ---------------------------------------------------------------------------
# end-to-end: aggregator over two shards, flows + device time + dumps
# ---------------------------------------------------------------------------

def _http_get(port, path):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read().decode()
    conn.close()
    return resp.status, body


def test_flight_e2e_aggregator_two_shards(beam_index, tmp_path):
    """THE acceptance loop: aggregator over two shard servers with the
    recorder on — one request id yields flow-connected spans on all
    three tiers, at least one engine segment carries sampled device
    time, the slow-query log carries the scheduler numbers, slow
    queries auto-dump, and the merge CLI joins per-tier dumps into one
    trace with globally recomputed flow arrows."""
    idx, data = beam_index
    dump_dir = str(tmp_path / "dumps")
    ctx_a = ServiceContext(ServiceSettings(default_max_result=3))
    ctx_a.add_index("shard_a", idx)
    ctx_b = ServiceContext(ServiceSettings(default_max_result=3))
    ctx_b.add_index("shard_b", idx)       # same snapshot, two tiers
    srv_a = SearchServer(ctx_a, batch_window_ms=1.0, metrics_port=-1,
                         slow_query_threshold_ms=1e-6,
                         flight_recorder=True, flight_dump_dir=dump_dir,
                         flight_tier="server_a")
    srv_b = SearchServer(ctx_b, batch_window_ms=1.0,
                         slow_query_threshold_ms=1e-6,
                         flight_recorder=True, flight_dump_dir=dump_dir,
                         flight_tier="server_b")
    ta, tb = _ServerThread(srv_a), _ServerThread(srv_b)
    ta.start()
    tb.start()
    # generous readiness timeouts: in-suite CPU contention (XLA compile
    # threads from earlier modules) can stall loop startup past the
    # harness default — the known flake mode of the PR-2 observability
    # e2e
    (ha, pa), (hb, pb) = ta.wait_ready(60), tb.wait_ready(60)
    agg_ctx = AggregatorContext(search_timeout_s=30.0,
                                flight_recorder=True,
                                flight_dump_on_slow_query=dump_dir,
                                slow_query_threshold_ms=1e-6)
    agg_ctx.servers = [RemoteServer(ha, pa), RemoteServer(hb, pb)]
    agg = AggregatorService(agg_ctx)
    tg = _ServerThread(agg)
    tg.start()
    hg, pg = tg.wait_ready(60)

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    shard_log = logging.getLogger("sptag_tpu.serve.server")
    capture = Capture()
    shard_log.addHandler(capture)
    rid = "e2e-flight-0042"
    try:
        from sptag_tpu.serve.client import AnnClient

        client = AnnClient(hg, pg, timeout_s=30.0)
        client.connect()
        qtext = ("$indexname:shard_a,shard_b $maxcheck:64 "
                 + "|".join(str(x) for x in data[5]))
        res = client.search(qtext, request_id=rid)
        assert res.status == wire.ResultStatus.Success
        assert res.request_id == rid
        client.close()

        # slow-query enrichment: the shard log line carries the per-rid
        # scheduler numbers next to the per-stage timings.  The shard
        # logs AFTER its response is already on the wire, so the client
        # can return first — poll briefly.
        deadline = time.time() + 10
        while time.time() < deadline:
            if any(("rid=%s" % rid) in m and "slot_wait=" in m
                   and "segments=" in m and "refills=" in m
                   for m in records):
                break
            time.sleep(0.05)
        assert any(("rid=%s" % rid) in m and "slot_wait=" in m
                   and "segments=" in m and "refills=" in m
                   for m in records), records

        # /debug/flight serves the Perfetto artifact; one rid spans all
        # three tiers (same post-response race: poll)
        deadline = time.time() + 10
        while time.time() < deadline:
            status, body = _http_get(srv_a._metrics_http.port,
                                     "/debug/flight")
            assert status == 200
            trace = json.loads(body)
            evs = trace["traceEvents"]
            rid_tiers = {e.get("cat") for e in evs
                         if e.get("args", {}).get("rid") == rid
                         and e["ph"] in ("X", "i")}
            if {"aggregator", "server_a", "server_b"} <= rid_tiers:
                break
            time.sleep(0.05)
        assert {"aggregator", "server_a", "server_b"} <= rid_tiers, rid_tiers
        # client + scheduler attribution ride the same trace in-process
        assert "client" in rid_tiers and "scheduler" in rid_tiers
        # flow arrows stitch the rid across tiers: one chain, one id
        flows = [e for e in evs if e.get("cat") == "flight.flow"
                 and e["id"] == flightrec._flow_id(rid)]
        assert {"s", "f"} <= {f["ph"] for f in flows}
        flow_pids = {f["pid"] for f in flows}
        pid_names = {e["pid"]: e["args"]["name"] for e in evs
                     if e["ph"] == "M" and e["name"] == "process_name"}
        assert {"aggregator", "server_a", "server_b"} <= \
            {pid_names[p] for p in flow_pids}
        # sampled device time: an engine segment with a real duration
        dev = [e for e in evs if e["ph"] == "X"
               and e["name"] == "segment_device" and e["cat"] == "engine"]
        assert dev and all(e["dur"] > 0 for e in dev)
        # server stages all present for the rid
        stage_names = {e["name"] for e in evs
                       if e.get("args", {}).get("rid") == rid}
        for want in ("decode", "queue_wait", "encode", "drain", "request",
                     "fanout", "merge", "send"):
            assert want in stage_names, (want, stage_names)

        # FlightDumpOnSlowQuery: the 1e-6 threshold makes every request
        # slow -> at least one ringed auto-dump lands on disk
        deadline = time.time() + 10
        while time.time() < deadline:
            dumps = [fn for fn in os.listdir(dump_dir)
                     if fn.endswith(".json")] if os.path.isdir(dump_dir) \
                else []
            if dumps:
                break
            time.sleep(0.05)
        assert dumps, "no auto-dump written"

        # merge CLI: split the ring into PER-TIER dumps (what separate
        # processes would produce), merge, and check the flow chain is
        # recomputed globally — no single input could contain it
        raw = flightrec.collect()
        ins = []
        for i, tiers in enumerate((("aggregator", "client"),
                                   ("server_a", "scheduler", "engine"),
                                   ("server_b",))):
            part = [e for e in raw if e["tier"] in tiers]
            assert part, tiers
            p = str(tmp_path / ("tier%d.json" % i))
            with open(p, "w") as f:
                json.dump({"traceEvents": [], "flightEvents": part}, f)
            ins.append(p)
        out = str(tmp_path / "merged.json")
        assert flight_cli.main(["-o", out, "--rid", rid] + ins) == 0
        with open(out) as f:
            merged = json.load(f)
        mevs = merged["traceEvents"]
        mtiers = {e.get("cat") for e in mevs
                  if e.get("args", {}).get("rid") == rid}
        assert {"aggregator", "server_a", "server_b"} <= mtiers
        mflows = [e for e in mevs if e.get("cat") == "flight.flow"]
        assert {"s", "f"} <= {f["ph"] for f in mflows}
        assert len({f["pid"] for f in mflows}) >= 3
        # --rid filter dropped untagged pool events (e.g. segment)
        assert all(e.get("args", {}).get("rid") == rid
                   for e in mevs if e["ph"] in ("X", "i"))
    finally:
        shard_log.removeHandler(capture)
        tg.stop()
        ta.stop()
        tb.stop()


def test_merge_cli_rejects_non_dump(tmp_path):
    p = str(tmp_path / "plain.json")
    with open(p, "w") as f:
        json.dump({"traceEvents": []}, f)
    assert flight_cli.main(["-o", "-", p]) == 1


# ---------------------------------------------------------------------------
# FlightRecorder=off: byte parity + zero hot-path work
# ---------------------------------------------------------------------------

def test_flight_off_parity_serve_bytes_and_zero_work():
    """With the recorder off (the default), the serve path produces
    byte-identical wire responses to the reference layout (golden bytes
    constructed from the executor + header spec) and performs zero
    recorder work — no events, no buffers (the ci_check.sh standalone
    parity pass)."""
    rng = np.random.default_rng(0)
    data = rng.standard_normal((50, 8)).astype(np.float32)
    index = sp.create_instance("FLAT", "Float")
    index.set_parameter("DistCalcMethod", "L2")
    index.build(data)
    ctx = ServiceContext(ServiceSettings(default_max_result=5))
    ctx.add_index("main", index)
    server = SearchServer(ctx, batch_window_ms=1.0)
    t = _ServerThread(server)
    t.start()
    host, port = t.wait_ready()
    try:
        assert not flightrec.enabled()
        qtext = "|".join(str(x) for x in data[7])
        # golden response bytes: executor result (rid stays empty), the
        # documented header fields (first connection -> cid 1)
        expected_result = SearchExecutor(ctx).execute(qtext)
        expected_result.request_id = ""
        expected_body = expected_result.pack()
        expected = wire.PacketHeader(
            wire.PacketType.SearchResponse, wire.PacketProcessStatus.Ok,
            len(expected_body), 1, 77).pack() + expected_body

        body = wire.RemoteQuery(qtext).pack()        # minor version 0
        assert body[2:4] == b"\x00\x00"
        s = socket.create_connection((host, port), timeout=10)
        s.sendall(wire.PacketHeader(
            wire.PacketType.SearchRequest, wire.PacketProcessStatus.Ok,
            len(body), 0, 77).pack() + body)
        s.settimeout(10)
        got = b""
        while len(got) < len(expected):
            chunk = s.recv(65536)
            if not chunk:
                break
            got += chunk
        s.close()
        assert got == expected
        c = flightrec.counters()
        assert c == {"enabled": 0, "recorded": 0, "dropped": 0,
                     "threads": 0, "dump_errors": 0,
                     "dump_ratelimited": 0}
    finally:
        t.stop()
