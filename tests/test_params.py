"""Parameter registry parity tests (reference X-macro registry,
inc/Core/BKT/ParameterDefinitionList.h + BKTIndex.cpp:537-573)."""

from sptag_tpu.core.params import BKTParams, KDTParams
from sptag_tpu.core.types import DistCalcMethod


def test_bkt_defaults_match_reference():
    p = BKTParams()
    assert p.get_param("BKTNumber") == "1"
    assert p.get_param("BKTKmeansK") == "32"
    assert p.get_param("BKTLeafSize") == "8"
    assert p.get_param("Samples") == "1000"
    assert p.get_param("TPTNumber") == "32"
    assert p.get_param("TPTLeafSize") == "2000"
    assert p.get_param("NeighborhoodSize") == "32"
    assert p.get_param("GraphNeighborhoodScale") == "2"
    assert p.get_param("CEF") == "1000"
    assert p.get_param("AddCEF") == "500"
    assert p.get_param("MaxCheckForRefineGraph") == "8192"
    assert p.get_param("DistCalcMethod") == "Cosine"
    assert p.get_param("MaxCheck") == "8192"
    assert p.get_param("NumberOfInitialDynamicPivots") == "50"
    assert p.get_param("NumberOfOtherDynamicPivots") == "4"
    assert p.get_param("DeletePercentageForRefine") == "0.4"
    assert p.get_param("AddCountForRebuild") == "1000"
    assert (p.get_param("ThresholdOfNumberOfContinuousNoBetterPropagation")
            == "3")
    assert p.get_param("TreeFilePath") == "tree.bin"


def test_kdt_defaults_match_reference():
    p = KDTParams()
    assert p.get_param("KDTNumber") == "1"
    assert p.get_param("NumTopDimensionKDTSplit") == "5"
    assert p.get_param("Samples") == "100"
    assert p.get_param("NumTopDimensionTPTSplit") == "5"


def test_set_param_case_insensitive_and_typed():
    p = BKTParams()
    assert p.set_param("maxcheck", "2048")
    assert p.max_check == 2048
    assert p.set_param("DistCalcMethod", "L2")
    assert p.dist_calc_method == DistCalcMethod.L2
    assert p.get_param("DistCalcMethod") == "L2"
    assert not p.set_param("NoSuchParam", "1")
    assert p.get_param("NoSuchParam") is None


def test_save_config_round_trip():
    p = BKTParams()
    p.set_param("MaxCheck", "4096")
    text = p.save_config()
    assert "MaxCheck=4096" in text
    q = BKTParams()
    section = dict(line.split("=", 1) for line in text.strip().splitlines())
    q.load_config(section)
    assert q.max_check == 4096
    assert q.save_config() == text


def test_memory_estimators_reference_formula():
    """Parity with VectorIndex::EstimatedMemoryUsage/EstimatedVectorCount
    (VectorIndex.cpp:403-437): per-row unit = value bytes * dim + 8 (meta
    offset) + 4 * neighborhood (graph) + 1 (tombstone) + tree nodes."""
    import sptag_tpu as sp

    # BKT float, d=128, m=32, 1 tree: 512 + 8 + 128 + 1 + 12 = 661 B/row
    assert sp.estimated_memory_usage(1, 128, "BKT", "Float") == 661
    assert sp.estimated_memory_usage(1000, 128, "BKT", "Float") == 661_000
    # KDT node = 16 B; int8 vector = 128 B
    assert sp.estimated_memory_usage(1, 128, "KDT", "Int8") == \
        128 + 8 + 128 + 1 + 16
    # inverse relation
    n = sp.estimated_vector_count(1 << 30, 128, "BKT", "Float")
    assert n == (1 << 30) // 661
    # hbm estimate is positive and grows with n
    a = sp.estimated_hbm_usage(1000, 128, "Float")
    b = sp.estimated_hbm_usage(2000, 128, "Float")
    assert 0 < a < b


def test_refine_accuracy_floor_parameter():
    """RefineAccuracyFloor (ADVICE r5): the guard's rollback floor is a
    tunable parameter next to RefineAccuracyGuard, not a hardcoded 0.35,
    and it flows from the registry into the RNG graph builder."""
    p = BKTParams()
    assert p.get_param("RefineAccuracyFloor") == "0.35"
    assert p.set_param("RefineAccuracyFloor", "0.2")
    assert p.refine_accuracy_floor == 0.2
    # present in both graph-index registries
    assert KDTParams().get_param("RefineAccuracyFloor") == "0.35"
    # config round trip
    text = p.save_config()
    assert "RefineAccuracyFloor=0.2" in text
    q = BKTParams()
    q.load_config(dict(line.split("=", 1)
                       for line in text.strip().splitlines()))
    assert q.refine_accuracy_floor == 0.2
    # reaches the graph builder (algo/bkt._new_graph -> rng ctor)
    import sptag_tpu as sp

    idx = sp.create_instance("BKT", "Float")
    assert idx.set_parameter("RefineAccuracyFloor", "0.15")
    g = idx._new_graph()
    assert g.refine_accuracy_floor == 0.15
    assert g.refine_accuracy_guard
