"""Parameter registry parity tests (reference X-macro registry,
inc/Core/BKT/ParameterDefinitionList.h + BKTIndex.cpp:537-573)."""

from sptag_tpu.core.params import BKTParams, KDTParams
from sptag_tpu.core.types import DistCalcMethod


def test_bkt_defaults_match_reference():
    p = BKTParams()
    assert p.get_param("BKTNumber") == "1"
    assert p.get_param("BKTKmeansK") == "32"
    assert p.get_param("BKTLeafSize") == "8"
    assert p.get_param("Samples") == "1000"
    assert p.get_param("TPTNumber") == "32"
    assert p.get_param("TPTLeafSize") == "2000"
    assert p.get_param("NeighborhoodSize") == "32"
    assert p.get_param("GraphNeighborhoodScale") == "2"
    assert p.get_param("CEF") == "1000"
    assert p.get_param("AddCEF") == "500"
    assert p.get_param("MaxCheckForRefineGraph") == "8192"
    assert p.get_param("DistCalcMethod") == "Cosine"
    assert p.get_param("MaxCheck") == "8192"
    assert p.get_param("NumberOfInitialDynamicPivots") == "50"
    assert p.get_param("NumberOfOtherDynamicPivots") == "4"
    assert p.get_param("DeletePercentageForRefine") == "0.4"
    assert p.get_param("AddCountForRebuild") == "1000"
    assert (p.get_param("ThresholdOfNumberOfContinuousNoBetterPropagation")
            == "3")
    assert p.get_param("TreeFilePath") == "tree.bin"


def test_kdt_defaults_match_reference():
    p = KDTParams()
    assert p.get_param("KDTNumber") == "1"
    assert p.get_param("NumTopDimensionKDTSplit") == "5"
    assert p.get_param("Samples") == "100"
    assert p.get_param("NumTopDimensionTPTSplit") == "5"


def test_set_param_case_insensitive_and_typed():
    p = BKTParams()
    assert p.set_param("maxcheck", "2048")
    assert p.max_check == 2048
    assert p.set_param("DistCalcMethod", "L2")
    assert p.dist_calc_method == DistCalcMethod.L2
    assert p.get_param("DistCalcMethod") == "L2"
    assert not p.set_param("NoSuchParam", "1")
    assert p.get_param("NoSuchParam") is None


def test_save_config_round_trip():
    p = BKTParams()
    p.set_param("MaxCheck", "4096")
    text = p.save_config()
    assert "MaxCheck=4096" in text
    q = BKTParams()
    section = dict(line.split("=", 1) for line in text.strip().splitlines())
    q.load_config(section)
    assert q.max_check == 4096
    assert q.save_config() == text
