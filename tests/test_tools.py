"""Reader + CLI tools tests (reference has none for its CLIs; the reader
format follows DefaultReader semantics, SURVEY.md §2d S6-S8)."""

import subprocess
import sys

import numpy as np

import sptag_tpu as sp
from sptag_tpu.core.types import VectorValueType
from sptag_tpu.io import format as fmt
from sptag_tpu.io.reader import ReaderOptions, VectorSetReader, load_vectors
from sptag_tpu.tools import index_builder, index_searcher


def _write_tsv(path, data, metas, delim="|"):
    with open(path, "wb") as f:
        for row, meta in zip(data, metas):
            vec = delim.join(repr(float(x)) for x in row)
            f.write(meta + b"\t" + vec.encode() + b"\n")


def test_reader_parses_tsv_parallel(tmp_path):
    rng = np.random.default_rng(0)
    data = rng.standard_normal((500, 10)).astype(np.float32)
    metas = [f"meta{i}".encode() for i in range(500)]
    path = str(tmp_path / "vec.tsv")
    _write_tsv(path, data, metas)

    reader = VectorSetReader(ReaderOptions(
        value_type=VectorValueType.Float, dimension=10, thread_num=8))
    assert reader.load_file(path)
    np.testing.assert_allclose(reader.vectors, data, rtol=1e-6)
    assert reader.metadata == metas

    # round-trip through the reference binary triple
    reader.save(str(tmp_path))
    back = fmt.read_matrix(str(tmp_path / "vectors.bin"), np.float32)
    np.testing.assert_allclose(back, data, rtol=1e-6)
    ms = sp.MetadataSet.load(str(tmp_path / "metadata.bin"),
                             str(tmp_path / "metadataIndex.bin"))
    assert ms.get_metadata(7) == b"meta7"


def test_load_vectors_bin_prefix(tmp_path):
    data = np.arange(24, dtype=np.float32).reshape(6, 4)
    path = str(tmp_path / "v.bin")
    fmt.write_matrix(path, data)
    vs, meta = load_vectors("BIN:" + path, ReaderOptions(
        value_type=VectorValueType.Float))
    np.testing.assert_allclose(vs.data, data)
    assert meta is None


def test_builder_and_searcher_cli(tmp_path):
    rng = np.random.default_rng(1)
    centers = rng.standard_normal((8, 12)).astype(np.float32) * 4
    data = (centers[rng.integers(0, 8, 300)]
            + rng.standard_normal((300, 12)).astype(np.float32))
    metas = [f"m{i}".encode() for i in range(300)]
    tsv = str(tmp_path / "corpus.tsv")
    _write_tsv(tsv, data, metas)

    out = str(tmp_path / "index")
    rc = index_builder.main([
        "-d", "12", "-v", "Float", "-i", tsv, "-o", out, "-a", "BKT",
        "-t", "4",
        "Index.DistCalcMethod=L2", "Index.BKTKmeansK=8",
        "Index.TPTNumber=4", "Index.TPTLeafSize=64",
        "Index.NeighborhoodSize=16", "Index.CEF=64",
        "Index.MaxCheckForRefineGraph=128", "Index.RefineIterations=1",
        "Index.Samples=100", "Index.DenseClusterSize=64"])
    assert rc == 0

    # exact truth for recall
    qs = data[:40]
    diff = qs[:, None, :] - data[None, :, :]
    exact = np.argsort((diff * diff).sum(-1), axis=1)[:, :5]
    truth_path = str(tmp_path / "truth.txt")
    with open(truth_path, "w") as f:
        for row in exact:
            f.write(" ".join(str(int(v)) for v in row) + "\n")
    qtsv = str(tmp_path / "queries.tsv")
    _write_tsv(qtsv, qs, [b""] * len(qs))

    flight_path = str(tmp_path / "flight.json")
    rc = index_searcher.main([
        "-x", out, "-q", qtsv, "-r", truth_path, "-k", "5",
        "-m", "256", "-o", str(tmp_path / "results.txt"),
        "--flight-dump", flight_path,
        "Index.SearchMode=beam", "Index.BeamSegmentIters=2",
        "Index.FlightDeviceSampleRate=1"])
    assert rc == 0
    lines = open(str(tmp_path / "results.txt")).read().splitlines()
    assert len(lines) == 40
    first = [int(t) for t in lines[0].split()]
    assert first[0] == 0      # self-query
    # --flight-dump (ISSUE 5 satellite): the offline run writes the SAME
    # Perfetto artifact the serving tier exports, with sampled engine
    # device time from the segmented walk
    import json as jsonmod
    with open(flight_path) as f:
        trace = jsonmod.load(f)
    assert trace["otherData"]["tool"] == "index_searcher"
    names = {ev["name"] for ev in trace["traceEvents"]}
    assert "segment_device" in names
    assert any(e["kind"] == "segment_device" and e["dur_ns"] > 0
               for e in trace["flightEvents"])


def test_calc_recall():
    ids = np.asarray([[0, 1, 2], [3, 4, 5]])
    truth = [{0, 1, 9}, {9, 8, 7}]
    assert index_searcher.calc_recall(ids, truth, 3) == (2 / 3 + 0) / 2
