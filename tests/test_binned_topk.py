"""ISSUE 13 — bin-reduction top-k (ops/topk_bins.py + the BinnedTopK wiring).

Four contracts under test:

* the PRIMITIVE: `binned_topk` matches `lax.top_k` exactly whenever no
  two winners collide in a bin (bins >= width is always exact), ties
  resolve to the lowest index like `top_k`, and measured recall over
  random rows meets the `bins_for` target — including adversarial
  near-tie and clustered-winner distributions;
* the WALK: with BinnedTopK on, segmented and monolithic walks stay
  bit-identical (the scheduler's retire contract), the scheduler path
  returns the monolithic ids, and end recall on a real kNN graph stays
  close to the exact walk's;
* the MESH: monolithic sharded search and the mesh scheduler path stay
  id-identical with BinnedTopK on (the shared walk_merge_bins rule);
* OFF-PARITY: with BinnedTopK at its default (off) every engine resolves
  bins=0, results are bit-identical to an engine that never heard of the
  parameter, and serve wire bytes match the reference layout (the
  ci_check.sh standalone pass).

Corpora are tiny: what is under test is selection algebra and parity,
not throughput — the bench owns the perf claim.
"""

import math
import socket

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import sptag_tpu as sp
from sptag_tpu.core.types import DistCalcMethod
from sptag_tpu.ops import topk_bins

# ---------------------------------------------------------------------------
# primitive: math + exactness + ties
# ---------------------------------------------------------------------------


def test_bins_for_math():
    # inverts E[recall] ~ exp(-k(k-1)/2bins); floors at 2k, caps at width
    assert topk_bins.bins_for(1, 1 << 20, 0.5) == 2       # 2k floor
    b99 = topk_bins.bins_for(10, 1 << 20, 0.99)
    b95 = topk_bins.bins_for(10, 1 << 20, 0.95)
    assert b99 > b95 >= 512                  # tighter target -> more bins
    need = 10 * 9 / (2 * math.log(1 / 0.95))
    assert b95 == topk_bins.pow2ceil(int(math.ceil(need)))
    assert topk_bins.bins_for(10, 256, 0.99) == 256       # width cap
    assert topk_bins.bins_for(10, 300, 1.0) == 512        # exact: pow2(width)


def test_recall_target_validation():
    for bad in (0.0, -0.1, 1.5):
        with pytest.raises(ValueError):
            topk_bins.validate_recall_target(bad)
    assert topk_bins.validate_recall_target(1.0) == 1.0


def test_resolve_and_merge_bin_rules():
    assert topk_bins.resolve_bins("off", 10, 4096) == 0
    assert topk_bins.resolve_bins("0", 10, 4096) == 0
    # at the default 0.99 target, k=10 wants 8192 bins — a 4096-wide
    # row stays exact even under "on"; a looser target engages
    assert topk_bins.resolve_bins("on", 10, 4096) == 0
    assert topk_bins.resolve_bins("on", 10, 4096, 0.95) == 1024
    # auto declines narrow rows, engages wide ones
    assert topk_bins.resolve_bins("auto", 10, 64) == 0
    assert topk_bins.resolve_bins("auto", 10, 1 << 16, 0.95) > 0
    with pytest.raises(ValueError):
        topk_bins.resolve_bins("maybe", 10, 4096)
    # the walk-merge rule: bins always covers the sorted beam prefix
    # twice over (measured recall tradeoff — see walk_merge_bins)
    for L in (3, 64, 320, 1000):
        bins = topk_bins.walk_merge_bins("on", L, L + 4096)
        assert bins >= 2 * L and bins == topk_bins.pow2ceil(2 * L)
    assert topk_bins.walk_merge_bins("off", 64, 4096) == 0
    # auto: narrow candidate block -> stay exact
    assert topk_bins.walk_merge_bins("auto", 64, 96) == 0
    # binned seeding: spare queue truncates to 3L when the pivot pool is
    # wide enough to make the reduction pay; off/narrow -> exact
    assert topk_bins.seed_spare_keep("off", 64, 8192) == 0
    assert topk_bins.seed_spare_keep("on", 64, 8192) == 192
    assert topk_bins.seed_spare_keep("on", 64, 300) == 0


def test_binned_topk_exact_when_bins_cover_width():
    rng = np.random.default_rng(0)
    d = jnp.asarray(rng.standard_normal((8, 100)).astype(np.float32))
    bins = topk_bins.pow2ceil(100)
    vals, idx = topk_bins.binned_topk_kernel(d, 10, bins)
    neg, ref = jax.lax.top_k(-d, 10)
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(ref))
    np.testing.assert_array_equal(np.asarray(vals), -np.asarray(neg))


def test_binned_topk_tie_rule_matches_top_k():
    # duplicated minimum values: both the bin argmin (lowest stride) and
    # the shortlist top_k (lowest index) must resolve like lax.top_k
    d = np.full((1, 64), 5.0, np.float32)
    d[0, [3, 35]] = 1.0          # same bin (32 bins): col 3 wins
    d[0, [10, 20]] = 2.0         # different bins: both survive
    vals, idx = topk_bins.binned_topk_kernel(jnp.asarray(d), 4, 32)
    assert idx[0, 0] == 3                      # tie -> lowest column
    assert set(np.asarray(idx[0, 1:3]).tolist()) == {10, 20}


# ---------------------------------------------------------------------------
# primitive: recall floors (random + adversarial distributions)
# ---------------------------------------------------------------------------


def _measured_recall(d, k, bins):
    vals, idx = topk_bins.binned_topk_kernel(jnp.asarray(d), k, bins)
    _, ref = jax.lax.top_k(-jnp.asarray(d), k)
    idx, ref = np.asarray(idx), np.asarray(ref)
    hits = [len(set(idx[i].tolist()) & set(ref[i].tolist()))
            for i in range(d.shape[0])]
    return float(np.mean(hits)) / k


@pytest.mark.parametrize("N,k,rt", [(4096, 10, 0.95), (4096, 10, 0.99),
                                    (16384, 32, 0.95), (1024, 1, 0.9)])
def test_recall_floor_random_rows(N, k, rt):
    """Measured recall over uniform rows meets the bins_for target minus
    sampling slack (3 sigma over rows*k Bernoulli trials)."""
    rng = np.random.default_rng(42)
    rows = 64
    d = rng.standard_normal((rows, N)).astype(np.float32)
    bins = topk_bins.bins_for(k, N, rt)
    rec = _measured_recall(d, k, bins)
    slack = 3.0 * math.sqrt(rt * (1 - rt) / (rows * k)) + 1e-9
    assert rec >= rt - slack - 0.01, (rec, rt, bins)


def test_recall_floor_adversarial_near_ties():
    """Near-tie distributions: the true top-k all within float eps of
    each other (tie-ordering churn) and CLUSTERED in adjacent columns —
    the strided binning must spread adjacent winners across bins."""
    rng = np.random.default_rng(7)
    rows, N, k = 64, 4096, 10
    d = rng.uniform(1.0, 2.0, (rows, N)).astype(np.float32)
    start = rng.integers(0, N - k, rows)
    for i in range(rows):
        # k adjacent near-tied winners (spacing < any bin stride)
        d[i, start[i]:start[i] + k] = 0.5 + np.arange(k) * 1e-6
    bins = topk_bins.bins_for(k, N, 0.95)
    rec = _measured_recall(d, k, bins)
    # adjacent columns land in k DISTINCT bins (strided rule): exact
    assert rec == 1.0, rec


def test_recall_collapses_only_on_same_bin_collisions():
    """The documented failure mode: winners exactly `bins` columns apart
    share a bin and only one survives — the contract the recall-target
    math prices in (uniform rows almost never do this)."""
    N, k = 16384, 8
    bins = topk_bins.bins_for(k, N, 0.95)
    d = np.ones((1, N), np.float32)
    d[0, np.arange(k) * bins] = 0.0        # all k in bin 0
    rec = _measured_recall(d, k, bins)
    assert rec == pytest.approx(1.0 / k)


# ---------------------------------------------------------------------------
# walk: recall vs exact + parity with BinnedTopK on
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def knn_setup():
    """Small corpus with a TRUE kNN graph: walk recall is meaningful."""
    rng = np.random.default_rng(5)
    N, D, m = 1500, 24, 12
    data = rng.standard_normal((N, D)).astype(np.float32)
    sq = (data ** 2).sum(1)
    d2 = sq[:, None] + sq[None, :] - 2 * data @ data.T
    np.fill_diagonal(d2, np.inf)
    graph = np.argsort(d2, axis=1)[:, :m].astype(np.int32)
    pivots = rng.choice(N, 96, replace=False).astype(np.int32)
    queries = rng.standard_normal((24, D)).astype(np.float32)
    truth = np.argsort(sq[None, :] - 2 * queries @ data.T,
                       axis=1)[:, :10]
    return data, graph, pivots, queries, truth


def _recall(ids, truth):
    return float(np.mean([
        len(set(ids[i, :10].tolist()) & set(truth[i].tolist())) / 10
        for i in range(len(ids))]))


def test_binned_walk_recall_close_to_exact(knn_setup):
    from sptag_tpu.algo.engine import GraphSearchEngine

    data, graph, pivots, queries, truth = knn_setup
    kw = dict(max_check=256, beam_width=8)
    eng_off = GraphSearchEngine(data, graph, pivots, None,
                                DistCalcMethod.L2, 1, score_dtype="f32")
    eng_on = GraphSearchEngine(data, graph, pivots, None,
                               DistCalcMethod.L2, 1, score_dtype="f32",
                               binned_topk="on")
    _, i0 = eng_off.search(queries, 10, **kw)
    _, i1 = eng_on.search(queries, 10, **kw)
    r0, r1 = _recall(i0, truth), _recall(i1, truth)
    # lazy marking keeps shortlist-dropped candidates rediscoverable, so
    # the binned walk tracks the exact one closely at equal budget
    assert r1 >= r0 - 0.05, (r0, r1)
    # no duplicate ids may survive the binned merge/finalize
    for row in i1:
        live = row[row >= 0].tolist()
        assert len(set(live)) == len(live), row


def test_binned_segmented_parity(knn_setup):
    """Monolithic vs segmented walk, bit for bit, WITH the binned merge
    — the absorbing-state contract is body-independent."""
    from sptag_tpu.algo.engine import GraphSearchEngine

    data, graph, pivots, queries, _ = knn_setup
    eng = GraphSearchEngine(data, graph, pivots, None, DistCalcMethod.L2,
                            1, score_dtype="f32", binned_topk="on")
    for mc, bw, seg in [(128, 8, 2), (256, 4, 5)]:
        d0, i0 = eng.search(queries, 5, max_check=mc, beam_width=bw)
        d1, i1 = eng.search(queries, 5, max_check=mc, beam_width=bw,
                            segment_iters=seg)
        np.testing.assert_array_equal(i0, i1)
        np.testing.assert_array_equal(d0, d1)


def test_binned_scheduler_parity_bkt():
    """BKT index with BinnedTopK=on: the continuous-batching scheduler
    returns the monolithic ids (retire/compact/refill preserve the
    binned body's absorbing states exactly like the exact body's)."""
    rng = np.random.default_rng(11)
    data = rng.standard_normal((500, 16)).astype(np.float32)
    queries = rng.standard_normal((12, 16)).astype(np.float32)
    idx = sp.create_instance("BKT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    for n, v in [("BKTNumber", "1"), ("BKTKmeansK", "8"),
                 ("Samples", "200"), ("TPTNumber", "2"),
                 ("TPTLeafSize", "50"), ("NeighborhoodSize", "8"),
                 ("CEF", "64"), ("MaxCheckForRefineGraph", "128"),
                 ("RefineIterations", "1"), ("SearchMode", "beam"),
                 ("MaxCheck", "96"), ("BinnedTopK", "on")]:
        assert idx.set_parameter(n, v), n
    assert idx.build(data) == sp.ErrorCode.Success
    try:
        eng = idx._get_engine()
        assert eng.binned_mode == "on"
        _, i_mono = idx.search_batch(queries, 5)
        idx.set_parameter("ContinuousBatching", "1")
        _, i_cb = idx.search_batch(queries, 5)
        np.testing.assert_array_equal(i_mono, i_cb)
    finally:
        idx.close()


def test_binned_mode_validation():
    from sptag_tpu.algo.engine import GraphSearchEngine

    data = np.zeros((4, 8), np.float32)
    graph = np.zeros((4, 2), np.int32)
    with pytest.raises(ValueError):
        GraphSearchEngine(data, graph, np.zeros(1, np.int32), None,
                          DistCalcMethod.L2, 1, binned_topk="sideways")


# ---------------------------------------------------------------------------
# mesh: id-parity with BinnedTopK on (shared walk_merge_bins rule)
# ---------------------------------------------------------------------------


def test_mesh_binned_scheduler_matches_monolithic(host_mesh):
    from sptag_tpu.algo.scheduler import gather_futures
    from sptag_tpu.parallel.sharded import ShardedBKTIndex

    rng = np.random.default_rng(3)
    data = rng.standard_normal((256, 16)).astype(np.float32)
    q = rng.standard_normal((8, 16)).astype(np.float32)
    index = ShardedBKTIndex.build(
        data, DistCalcMethod.L2, mesh=host_mesh(2),
        params={"BKTNumber": 1, "BKTKmeansK": 4, "TPTNumber": 2,
                "TPTLeafSize": 32, "NeighborhoodSize": 8, "CEF": 16,
                "MaxCheckForRefineGraph": 64, "RefineIterations": 1,
                "MaxCheck": 128, "SearchMode": "beam",
                "BinnedTopK": "on"})
    assert index._binned_mode() == "on"
    d_mono, i_mono = index.search(q, 5)
    index.enable_continuous_batching(slots=32)
    d_cb, i_cb = gather_futures(index.submit_batch(q, 5), 5)
    np.testing.assert_array_equal(i_mono, i_cb)
    np.testing.assert_allclose(d_mono, d_cb, rtol=1e-5, atol=1e-6)
    index.retire_scheduler()


# ---------------------------------------------------------------------------
# off-parity: default off = bins 0 everywhere + reference wire bytes
# ---------------------------------------------------------------------------


def test_binned_off_parity_resolution():
    """Default params resolve bins=0 at every site: the engines run the
    EXACT kernels (merge_bins=0 compiles the legacy body unchanged)."""
    rng = np.random.default_rng(9)
    data = rng.standard_normal((300, 16)).astype(np.float32)
    idx = sp.create_instance("BKT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    for n, v in [("BKTNumber", "1"), ("BKTKmeansK", "8"),
                 ("Samples", "200"), ("TPTNumber", "2"),
                 ("TPTLeafSize", "50"), ("NeighborhoodSize", "8"),
                 ("CEF", "64"), ("MaxCheckForRefineGraph", "128"),
                 ("RefineIterations", "1"), ("MaxCheck", "96")]:
        idx.set_parameter(n, v)
    assert idx.build(data) == sp.ErrorCode.Success
    try:
        assert str(idx.get_parameter("BinnedTopK")) == "off"
        eng = idx._get_engine()
        assert eng.binned_mode == "off"
        k_eff, L, B, _, _ = eng.walk_plan(10, 96, 16)
        assert eng.merge_bins_for(L, B) == 0
        assert eng.finalize_bins_for(k_eff, L) == 0
    finally:
        idx.close()


def test_binned_off_parity_golden_bytes():
    """With BinnedTopK at its default, a served search response is
    byte-identical to the reference wire layout (the ci_check.sh
    standalone pass — pattern shared with every off-by-default knob)."""
    from conftest import ServerThread
    from sptag_tpu.serve import wire
    from sptag_tpu.serve.server import SearchServer
    from sptag_tpu.serve.service import (SearchExecutor, ServiceContext,
                                         ServiceSettings)

    rng = np.random.default_rng(13)
    data = rng.standard_normal((200, 12)).astype(np.float32)
    flat = sp.create_instance("FLAT", "Float")
    flat.set_parameter("DistCalcMethod", "L2")
    flat.build(data)
    ctx = ServiceContext(ServiceSettings(default_max_result=5))
    ctx.add_index("f", flat)
    server = SearchServer(ctx, batch_window_ms=1.0)
    t = ServerThread(server)
    t.start()
    host, port = t.wait_ready()
    try:
        qtext = "|".join(str(x) for x in data[3])
        expected_result = SearchExecutor(ctx).execute(qtext)
        expected_result.request_id = ""
        expected_body = expected_result.pack()
        expected = wire.PacketHeader(
            wire.PacketType.SearchResponse, wire.PacketProcessStatus.Ok,
            len(expected_body), 1, 99).pack() + expected_body
        body = wire.RemoteQuery(qtext).pack()
        s = socket.create_connection((host, port), timeout=10)
        s.sendall(wire.PacketHeader(
            wire.PacketType.SearchRequest, wire.PacketProcessStatus.Ok,
            len(body), 0, 99).pack() + body)
        s.settimeout(10)
        got = b""
        while len(got) < len(expected):
            chunk = s.recv(65536)
            if not chunk:
                break
            got += chunk
        s.close()
        assert got == expected
    finally:
        t.stop()
