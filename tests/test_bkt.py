"""BKT index end-to-end tests, modeled on the reference lifecycle suite
(Test/src/AlgoTest.cpp:112-188: Build -> Search -> Save -> Load -> Add ->
Delete) plus recall-vs-brute-force assertions the reference lacks
(SURVEY.md §4)."""

import numpy as np
import pytest

import sptag_tpu as sp
from sptag_tpu.core.types import DistCalcMethod


def _make_index(n=800, d=12, metric="L2", seed=11, mode="dense"):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((16, d)).astype(np.float32) * 4
    data = (centers[rng.integers(0, 16, n)]
            + rng.standard_normal((n, d)).astype(np.float32))
    queries = (centers[rng.integers(0, 16, 50)]
               + rng.standard_normal((50, d)).astype(np.float32))
    index = sp.create_instance("BKT", "Float")
    index.set_parameter("DistCalcMethod", metric)
    # small-corpus build params (defaults target million-scale)
    for name, value in [("BKTNumber", "1"), ("BKTKmeansK", "8"),
                        ("TPTNumber", "6"), ("TPTLeafSize", "64"),
                        ("NeighborhoodSize", "16"), ("CEF", "64"),
                        ("AddCEF", "32"), ("MaxCheckForRefineGraph", "256"),
                        ("MaxCheck", "512"), ("RefineIterations", "2"),
                        ("Samples", "100"), ("SearchMode", mode),
                        ("DenseClusterSize", "64")]:
        assert index.set_parameter(name, value)
    assert index.build(data) == sp.ErrorCode.Success
    return index, data, queries


def _oracle(index, data, queries, k):
    oracle = sp.create_instance("FLAT", "Float")
    oracle.set_parameter(
        "DistCalcMethod",
        "L2" if index.dist_calc_method == DistCalcMethod.L2 else "Cosine")
    oracle.build(data)
    return oracle.search_batch(queries, k)


@pytest.mark.parametrize("metric", ["L2", "Cosine"])
@pytest.mark.parametrize("mode", ["dense", "beam"])
def test_bkt_recall_vs_oracle(metric, mode):
    index, data, queries = _make_index(metric=metric, mode=mode)
    k = 10
    d_bkt, i_bkt = index.search_batch(queries, k)
    d_true, i_true = _oracle(index, data, queries, k)
    recall = np.mean([len(set(i_bkt[q].tolist()) & set(i_true[q].tolist()))
                      / k for q in range(len(queries))])
    assert recall >= 0.9, recall
    # distances ascending and consistent with ids
    assert np.all(np.diff(d_bkt, axis=1) >= -1e-4)


def test_bkt_self_query_exact():
    index, data, _ = _make_index()
    d, ids = index.search_batch(data[:20], 1)
    assert (ids[:, 0] == np.arange(20)).mean() >= 0.95
    assert np.allclose(d[ids[:, 0] == np.arange(20), 0], 0, atol=1e-4)


def test_bkt_save_load_roundtrip(tmp_path):
    index, data, queries = _make_index(n=400)
    folder = str(tmp_path / "bkt_index")
    assert index.save_index(folder) == sp.ErrorCode.Success
    loaded = sp.load_index(folder)
    assert loaded.algo == sp.IndexAlgoType.BKT
    assert loaded.num_samples == index.num_samples
    d0, i0 = index.search_batch(queries[:8], 5)
    d1, i1 = loaded.search_batch(queries[:8], 5)
    np.testing.assert_array_equal(i0, i1)
    np.testing.assert_allclose(d0, d1, rtol=1e-5)


def test_bkt_add_then_search_finds_new_rows():
    index, data, _ = _make_index(n=400)
    rng = np.random.default_rng(99)
    new = data[:16] + rng.standard_normal((16, data.shape[1])).astype(
        np.float32) * 0.01
    assert index.add(new) == sp.ErrorCode.Success
    assert index.num_samples == 416
    d, ids = index.search_batch(new, 3)
    hit = np.mean([(400 + q) in ids[q] for q in range(16)])
    assert hit >= 0.9, (hit, ids[:4])


def test_bkt_delete_and_refine():
    index, data, queries = _make_index(n=400)
    # delete-by-content: exact rows are tombstoned and vanish from results
    # (an ANN search backs the delete, exactly as in the reference
    # BKTIndex.cpp:439-453, so a rare miss is legal — require >=4 of 5)
    assert index.delete(data[:5]) == sp.ErrorCode.Success
    assert index.num_deleted >= 4
    gone = np.flatnonzero([not index.contains_sample(i) for i in range(5)])
    _, ids = index.search_batch(data[:5], 3)
    assert not np.isin(ids, gone).any()
    # compaction keeps search working
    assert index.refine_index() == sp.ErrorCode.Success
    assert index.num_deleted == 0
    assert index.num_samples <= 396
    d, ids = index.search_batch(queries[:10], 5)
    assert (ids[:, 0] >= 0).all()


def test_bkt_add_triggers_tree_rebuild():
    index, data, _ = _make_index(n=300)
    index.set_parameter("AddCountForRebuild", "32")
    rng = np.random.default_rng(5)
    new = rng.standard_normal((40, data.shape[1])).astype(np.float32)
    assert index.add(new) == sp.ErrorCode.Success
    assert index._adds_since_rebuild == 0   # rebuild fired
    d, ids = index.search_batch(new[:4], 1)
    assert (ids[:, 0] >= 300).all()


def test_bkt_beam_bf16_scoring_matches_f32():
    """BeamScoreDtype=bf16 (the TPU walk-scoring shadow corpus): recall
    must match the f32 walk and returned distances must be EXACT f32 —
    the final pool is re-ranked against the full-precision rows
    (engine._walk), so approximation stays confined to beam ORDERING."""
    rng = np.random.default_rng(3)
    data = rng.standard_normal((4000, 32)).astype(np.float32)
    queries = rng.standard_normal((32, 32)).astype(np.float32)
    dn = (data ** 2).sum(1)
    truth = np.argsort(dn[None, :] - 2 * (queries @ data.T), axis=1)[:, :10]

    def build(score_dtype):
        idx = sp.create_instance("BKT", "Float")
        idx.set_parameter("DistCalcMethod", "L2")
        for name, value in [("BKTNumber", "1"), ("BKTKmeansK", "8"),
                            ("TPTNumber", "2"), ("TPTLeafSize", "200"),
                            ("NeighborhoodSize", "16"), ("CEF", "64"),
                            ("MaxCheckForRefineGraph", "256"),
                            ("RefineIterations", "1"), ("MaxCheck", "1024"),
                            ("SearchMode", "beam"),
                            ("BeamScoreDtype", score_dtype)]:
            idx.set_parameter(name, value)
        idx.build(data)
        return idx

    def recall(ids):
        return np.mean([len(set(ids[i, :10]) & set(truth[i])) / 10
                        for i in range(len(truth))])

    d32, i32 = build("f32").search_batch(queries, 10)
    d16, i16 = build("bf16").search_batch(queries, 10)
    assert abs(recall(i16) - recall(i32)) <= 0.02, (recall(i16), recall(i32))
    # exact-distance guarantee of the rerank
    for r in range(8):
        for c in range(10):
            if i16[r, c] >= 0:
                exact = float(((queries[r] - data[i16[r, c]]) ** 2).sum())
                assert abs(float(d16[r, c]) - exact) < 1e-2


def test_bkt_int8_beam_mode_recall():
    """int8 cosine BEAM path (round-2 verdict: the int8 config was only
    ever benched in dense mode) — the walk must hit the same exact-integer
    ground truth the dense path is held to."""
    from sptag_tpu.ops.distance import normalize

    rng = np.random.default_rng(2)
    raw = rng.standard_normal((4000, 64)).astype(np.float32)
    data = np.clip(np.round(
        raw / np.linalg.norm(raw, axis=1, keepdims=True) * 127),
        -128, 127).astype(np.int8)
    queries = data[rng.integers(0, len(data), 32)]
    stored = normalize(data, 127).astype(np.int64)
    qn = normalize(queries, 127).astype(np.int64)
    truth = np.argsort(-(qn @ stored.T), axis=1)[:, :10]
    idx = sp.create_instance("BKT", "Int8")
    idx.set_parameter("DistCalcMethod", "Cosine")
    idx.set_parameter("SearchMode", "beam")
    for name, value in [("BKTNumber", "1"), ("BKTKmeansK", "8"),
                        ("TPTNumber", "2"), ("TPTLeafSize", "200"),
                        ("NeighborhoodSize", "16"), ("CEF", "64"),
                        ("MaxCheckForRefineGraph", "256"),
                        ("RefineIterations", "1"), ("MaxCheck", "1024")]:
        idx.set_parameter(name, value)
    idx.build(data)
    _, ids = idx.search_batch(queries, 10)
    r = np.mean([len(set(ids[i, :10]) & set(truth[i])) / 10
                 for i in range(len(truth))])
    assert r >= 0.9, r


def test_beam_width_budget_scaling():
    """B widens with MaxCheck (fewer serial device iterations at high
    budgets; the round-4 ladder measured recall RISING to B=256): the
    floor is the caller's BeamWidth (NEVER reduced, even above the auto
    cap of 128), the auto-scaled part is MaxCheck/32 capped at 128, and
    L bounds everything."""
    from sptag_tpu.algo.engine import beam_pool_size, beam_width_for

    def beff(beam_width, max_check, n=100_000, k=10):
        return beam_width_for(beam_width, max_check,
                              beam_pool_size(k, max_check, n))

    assert beff(16, 512) == 16          # floor holds at small budgets
    assert beff(16, 2048) == 64
    assert beff(16, 8192) == 128        # auto part capped
    assert beff(48, 1024) == 48         # explicit floor wins
    assert beff(256, 2048) == 256       # explicit width above cap honored


def test_grouped_refine_matches_ungrouped():
    """RefineQueryGroup routes the build-time refine searches through the
    grouped dense kernel (refine queries are corpus rows — maximally
    probe-local); graph quality must match the ungrouped refine.
    Measured at 20k: 1.8x faster build, identical recall."""
    rng = np.random.default_rng(9)
    centers = rng.standard_normal((32, 24)).astype(np.float32) * 3
    data = (centers[rng.integers(0, 32, 6000)]
            + rng.standard_normal((6000, 24)).astype(np.float32))
    queries = (centers[rng.integers(0, 32, 48)]
               + rng.standard_normal((48, 24)).astype(np.float32))
    dn = (data ** 2).sum(1)
    truth = np.argsort(dn[None, :] - 2 * (queries @ data.T), axis=1)[:, :10]

    def build(group):
        idx = sp.create_instance("BKT", "Float")
        idx.set_parameter("DistCalcMethod", "L2")
        idx.set_parameter("SearchMode", "beam")
        for name, value in [("BKTNumber", "1"), ("BKTKmeansK", "8"),
                            ("TPTNumber", "2"), ("TPTLeafSize", "300"),
                            ("NeighborhoodSize", "16"), ("CEF", "64"),
                            ("MaxCheckForRefineGraph", "512"),
                            ("RefineIterations", "2"), ("MaxCheck", "1024"),
                            ("RefineQueryGroup", str(group))]:
            idx.set_parameter(name, value)
        idx.build(data)
        _, ids = idx.search_batch(queries, 10)
        return np.mean([len(set(ids[i, :10]) & set(truth[i])) / 10
                        for i in range(len(truth))])

    r_un = build(0)
    r_gr = build(32)
    assert r_gr >= r_un - 0.03, (r_gr, r_un)
    assert r_gr >= 0.9, r_gr


def test_bkt_uint8_end_to_end():
    """UInt8 value type through the full index lifecycle (the distance
    kernels are golden-tested per dtype; this pins the index-level path:
    ingest normalization base 255, integer cosine convention, save/load)."""
    from sptag_tpu.ops.distance import normalize

    rng = np.random.default_rng(21)
    raw = rng.random((3000, 32)).astype(np.float32)
    data = np.clip(np.round(
        raw / np.linalg.norm(raw, axis=1, keepdims=True) * 255),
        0, 255).astype(np.uint8)
    queries = data[rng.integers(0, len(data), 24)]
    stored = normalize(data, 255).astype(np.int64)
    qn = normalize(queries, 255).astype(np.int64)
    truth = np.argsort(-(qn @ stored.T), axis=1)[:, :10]
    idx = sp.create_instance("BKT", "UInt8")
    idx.set_parameter("DistCalcMethod", "Cosine")
    # beam mode: the uniform-on-sphere corpus has no cluster structure for
    # the dense partition to exploit at this budget; the graph walk is the
    # reference-parity path this test pins
    idx.set_parameter("SearchMode", "beam")
    for name, value in [("BKTNumber", "1"), ("BKTKmeansK", "8"),
                        ("TPTNumber", "2"), ("TPTLeafSize", "200"),
                        ("NeighborhoodSize", "16"), ("CEF", "64"),
                        ("MaxCheckForRefineGraph", "256"),
                        ("RefineIterations", "1"), ("MaxCheck", "1024")]:
        idx.set_parameter(name, value)
    idx.build(data)
    _, ids = idx.search_batch(queries, 10)
    r = np.mean([len(set(ids[i, :10]) & set(truth[i])) / 10
                 for i in range(len(truth))])
    assert r >= 0.9, r


def test_beam_packed_neighbors_matches_row_gather():
    """BeamPackedNeighbors (VERDICT r3 item 3): the packed (N, m, D)
    neighbor-vector layout must produce IDENTICAL results to the
    row-gather walk — same ids, same distances — at m x corpus HBM; it
    only changes the gather pattern, never the scores.  Covers f32, the
    bf16 shadow combination, and int8."""
    rng = np.random.default_rng(17)

    def build(value_type, packed, score_dtype="f32"):
        d = 24
        if value_type == "Int8":
            data = rng.integers(-100, 100, (3000, d)).astype(np.int8)
        else:
            data = rng.standard_normal((3000, d)).astype(np.float32)
        idx = sp.create_instance("BKT", value_type)
        idx.set_parameter("DistCalcMethod", "L2")
        for name, value in [("BKTNumber", "1"), ("BKTKmeansK", "8"),
                            ("TPTNumber", "2"), ("TPTLeafSize", "200"),
                            ("NeighborhoodSize", "16"), ("CEF", "64"),
                            ("MaxCheckForRefineGraph", "256"),
                            ("RefineIterations", "1"),
                            ("MaxCheck", "1024"),
                            ("SearchMode", "beam"),
                            ("BeamScoreDtype", score_dtype),
                            ("BeamPackedNeighbors",
                             "1" if packed else "0")]:
            assert idx.set_parameter(name, value)
        idx.build(data)
        return idx, data

    for vt, sd in (("Float", "f32"), ("Float", "bf16"), ("Int8", "f32")):
        rng = np.random.default_rng(17)          # identical build inputs
        idx_row, data = build(vt, packed=False, score_dtype=sd)
        rng = np.random.default_rng(17)
        idx_pack, _ = build(vt, packed=True, score_dtype=sd)
        queries = (data[7:39].astype(np.float32)
                   + 0.1).astype(data.dtype)
        d_row, i_row = idx_row.search_batch(queries, 10)
        d_pack, i_pack = idx_pack.search_batch(queries, 10)
        assert np.array_equal(i_row, i_pack), (vt, sd)
        np.testing.assert_allclose(d_row, d_pack, rtol=1e-6,
                                   err_msg=f"{vt}/{sd}")
        assert idx_pack._get_engine().nbr_vecs is not None
        assert idx_row._get_engine().nbr_vecs is None


def test_starved_refine_budget_warns(caplog):
    """Round-5 guardrail (reports/SCALE.md): a dense refine whose budget
    probes <2 clusters of its partition must say so — at 10M that
    configuration silently replaced TPT edges with near-random results."""
    import logging

    data = np.random.default_rng(5).standard_normal(
        (2000, 24)).astype(np.float32)
    idx = sp.create_instance("BKT", "Float")
    for name, value in [("DistCalcMethod", "L2"),
                        ("RefineIterations", "1"),
                        ("RefineSearchMode", "dense"),
                        ("FinalRefineSearchMode", "same"),
                        # CEF low too: the effective budget the warning
                        # judges is max(budget, 2*(CEF+1))
                        ("CEF", "16"),
                        ("MaxCheckForRefineGraph", "8")]:
        assert idx.set_parameter(name, value)
    with caplog.at_level(logging.WARNING, logger="sptag_tpu.algo.bkt"):
        idx.build(data)
    assert any("probes only" in r.message for r in caplog.records), \
        [r.message for r in caplog.records]
