"""Runtime lock sanitizer (utils/locksan.py) + static/runtime graph
cross-check (ISSUE 3 acceptance).

Key proofs:

* a deliberately inverted lock pair is CAUGHT at runtime — raising
  `LockOrderError` in strict mode, bumping the ``locksan.inversions``
  counter and logging both stacks otherwise;
* the watchdog dumps every thread's held locks + stack when a lock wait
  exceeds the threshold;
* the order graph a real workload (a BKT index scheduling its background
  rebuild through the ThreadPool) observes at runtime is CONSISTENT with
  the static graph graftlint's GL7xx pass builds: merging the two graphs
  introduces no cycle, i.e. neither analysis knows an ordering the other
  contradicts.

The whole tier-1 suite runs with SPTAG_LOCKSAN=1 (tests/conftest.py), and
a conftest fixture fails any test that OBSERVES an inversion — so every
serve/index test doubles as a no-inversion probe; the deliberate
inversions here opt out via the ``locksan_ok`` marker.
"""

import threading

import numpy as np
import pytest

import sptag_tpu as sp
from sptag_tpu.utils import locksan, metrics


@pytest.fixture(autouse=True)
def _fresh_locksan():
    locksan.reset_observations()
    yield
    locksan.reset_config()       # the env (conftest: "1") decides again
    locksan.reset_observations()


# ---------------------------------------------------------------------------
# inversion detection
# ---------------------------------------------------------------------------

@pytest.mark.locksan_ok
def test_inversion_logged_and_counted_nonstrict(caplog):
    locksan.enable(strict=False)
    a = locksan.SanLock("test.A")
    b = locksan.SanLock("test.B")
    with a:
        with b:
            pass
    before = metrics.counter_value("locksan.inversions")
    with caplog.at_level("ERROR", logger="sptag_tpu.utils.locksan"):
        with b:
            with a:                  # inverts the observed A -> B order
                pass
    assert locksan.inversion_count() == 1
    assert metrics.counter_value("locksan.inversions") == before + 1
    rec = locksan.inversions()[0]
    assert rec["acquiring"] == "test.A" and rec["held"] == "test.B"
    # both stacks ride into the log: the established-order witness and
    # the inverted acquisition
    msgs = [r.getMessage() for r in caplog.records
            if "lock-order inversion" in r.getMessage()]
    assert msgs and "established at" in msgs[0] and \
        "inverted here" in msgs[0]
    # same pair again: still DETECTED (counter + record — strict mode
    # must refuse repeats and the per-test probe must see them), but the
    # stack-dump log is deduplicated per pair
    with caplog.at_level("ERROR", logger="sptag_tpu.utils.locksan"):
        with b:
            with a:
                pass
    assert locksan.inversion_count() == 2
    assert metrics.counter_value("locksan.inversions") == before + 2
    repeat_logs = [r for r in caplog.records
                   if "lock-order inversion" in r.getMessage()]
    assert len(repeat_logs) == 1, "repeat inversion must not re-log"


@pytest.mark.locksan_ok
def test_inversion_raises_in_strict_mode():
    locksan.enable(strict=True)
    a = locksan.SanLock("strict.A")
    b = locksan.SanLock("strict.B")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(locksan.LockOrderError, match="strict.A"):
            with a:
                pass
        # a RETRY of the same inverted pair must be refused again —
        # dedup applies to log spam, never to detection
        with pytest.raises(locksan.LockOrderError, match="strict.A"):
            with a:
                pass
    # the refused acquisition must NOT leave the lock held
    assert a.acquire(blocking=False)
    a.release()


@pytest.mark.locksan_ok
def test_transitive_inversion_detected():
    """A->B and B->C establish A ⇝ C; acquiring A under C inverts it even
    though the direct pair was never seen."""
    locksan.enable(strict=False)
    a, b, c = (locksan.SanLock(f"chain.{n}") for n in "ABC")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with c:
        with a:
            pass
    assert locksan.inversion_count() == 1
    assert locksan.inversions()[0]["acquiring"] == "chain.A"


def test_rlock_reentrancy_is_not_an_inversion():
    locksan.enable(strict=True)
    r = locksan.SanRLock("re.R")
    other = locksan.SanLock("re.other")
    with r:
        with other:
            with r:                  # reentrant: no new edge, no error
                pass
    assert locksan.inversion_count() == 0
    g = locksan.order_graph()
    assert "re.R" in g and "re.other" in g["re.R"]
    # re-acquisition under `other` added no other->R edge (would be a
    # false inversion seed)
    assert "re.R" not in g.get("re.other", set())


def test_make_lock_is_plain_when_disabled_sanitized_when_enabled():
    locksan.disable()
    plain = locksan.make_lock("x")
    assert not isinstance(plain, locksan.SanLock)
    locksan.enable()
    san = locksan.make_lock("x")
    assert isinstance(san, locksan.SanLock)
    assert isinstance(locksan.make_rlock("y"), locksan.SanRLock)


def test_held_stack_tracks_acquire_release():
    locksan.enable()
    lk = locksan.SanLock("held.one")
    tid = threading.get_ident()
    with lk:
        assert locksan.held_locks().get(tid) == ["held.one"]
    assert tid not in locksan.held_locks()


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_dumps_held_locks_and_stacks(caplog):
    locksan.enable(strict=False, watchdog_ms=50)
    lk = locksan.SanLock("wd.slow")
    holder_in = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            holder_in.set()
            release.wait(10)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert holder_in.wait(5)
    before = metrics.counter_value("locksan.watchdog_stalls")
    with caplog.at_level("WARNING", logger="sptag_tpu.utils.locksan"):
        def waiter():
            with lk:
                pass
        w = threading.Thread(target=waiter, daemon=True)
        w.start()
        w.join(0.3)                   # well past the 50 ms threshold
        release.set()
        w.join(5)
        t.join(5)
    assert metrics.counter_value("locksan.watchdog_stalls") >= before + 1
    dump = "\n".join(r.getMessage() for r in caplog.records
                     if "locksan watchdog" in r.getMessage())
    assert "wd.slow" in dump          # the stalled lock is named
    assert "holds" in dump            # per-thread held-lock listing


# ---------------------------------------------------------------------------
# static graph cross-check
# ---------------------------------------------------------------------------

def _static_id(static_ids, runtime_name):
    hits = [c for c in static_ids
            if c == runtime_name or c.endswith("." + runtime_name)]
    return hits[0] if len(hits) == 1 else None


def _has_path(edges, src, dst):
    seen, todo = set(), [src]
    while todo:
        n = todo.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        todo.extend(edges.get(n, ()))
    return False


def test_runtime_order_graph_consistent_with_static(tmp_path):
    """Drive a real nested-lock workload (BKT online adds scheduling the
    background rebuild pool under the index writer lock), then check the
    runtime-observed order graph against graftlint's static one: no
    runtime edge may close a cycle with the static edges."""
    import os
    from tools.graftlint.core import Project
    from tools.graftlint.lockgraph import build_order_graph

    locksan.enable(strict=True)      # any inversion in the workload raises
    locksan.reset_observations()

    rng = np.random.default_rng(7)
    data = rng.standard_normal((256, 16)).astype(np.float32)
    index = sp.create_instance("BKT", "Float")
    for name, value in [("DistCalcMethod", "L2"), ("BKTKmeansK", "8"),
                        ("TPTNumber", "2"), ("TPTLeafSize", "64"),
                        ("NeighborhoodSize", "8"), ("CEF", "32"),
                        ("MaxCheck", "256"), ("RefineIterations", "1"),
                        ("Samples", "64"), ("AddCountForRebuild", "32")]:
        index.set_parameter(name, value)
    assert index.build(data) == sp.ErrorCode.Success
    for i in range(0, 96, 32):       # trigger the background rebuild path
        extra = rng.standard_normal((32, 16)).astype(np.float32)
        assert index.add(extra) == sp.ErrorCode.Success
    index.wait_for_rebuild(30)
    index.close()

    observed = locksan.order_graph()
    # the workload really exercised the nested pair this test is about
    assert any("VectorIndex._lock" in a and
               any("ThreadPool._lock" in b for b in bs)
               for a, bs in observed.items()), observed

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    model, static_edges, _wit = build_order_graph(
        Project.from_tree(os.path.join(repo, "sptag_tpu")))
    # full static lock inventory, not just locks that appear in an edge —
    # a runtime name must map even when the static side saw no nesting
    static_ids = set(model.locks)
    for bs in static_edges.values():
        static_ids |= bs

    merged = {a: set(bs) for a, bs in static_edges.items()}
    checked = 0
    for a, bs in observed.items():
        ca = _static_id(static_ids, a) or a
        for b in bs:
            cb = _static_id(static_ids, b) or b
            # direct contradiction: static order says cb before ca
            assert not _has_path(static_edges, cb, ca), (
                f"runtime order {a} -> {b} contradicts the static graph")
            merged.setdefault(ca, set()).add(cb)
            checked += 1
    assert checked >= 1
    # merging runtime into static closes no cycle anywhere
    for node in list(merged):
        for nxt in merged[node]:
            assert not _has_path(merged, nxt, node), (
                f"cycle through {node} -> {nxt} after merging runtime "
                "and static order graphs")
