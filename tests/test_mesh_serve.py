"""In-mesh sharded serving (ISSUE 11) — tier-1 mesh tests.

Small corpora on 2-4 virtual CPU devices (the conftest `host_mesh`
helper; the suite boots with 8 forced host devices) so the mesh serve
spine is exercised in tier-1 instead of living behind `slow` markers:

* the shard_map compat shim (jax.shard_map vs the experimental module);
* the merge contract: the in-mesh path returns the SAME ids as the
  socket fan-out aggregator + host merge over identical shard contents,
  across k / MaxCheck / deleted-mask cases;
* the mesh-wide slot scheduler (parallel/mesh_engine.py under
  algo/scheduler.py) returning search()'s ids in retire order;
* MeshServe end-to-end over sockets (streaming responses, mesh
  admission signals, epoch swap, /healthz mutation state);
* MeshServe OFF: serve bytes byte-identical (the ci_check.sh
  off-parity pass).
"""

import base64
import socket

import numpy as np
import pytest

import jax

from sptag_tpu.core.types import DistCalcMethod
from sptag_tpu.parallel.sharded import (
    ServingAdapter,
    ShardedBKTIndex,
    ShardedFlatIndex,
    make_mesh,
)
from sptag_tpu.serve import wire
from sptag_tpu.serve.client import AnnClient
from sptag_tpu.serve.server import SearchServer
from sptag_tpu.serve.service import (
    SearchExecutor,
    ServiceContext,
    ServiceSettings,
)
from sptag_tpu.utils import metrics

TINY_PARAMS = {"BKTNumber": 1, "BKTKmeansK": 4, "TPTNumber": 2,
               "TPTLeafSize": 32, "NeighborhoodSize": 8, "CEF": 16,
               "MaxCheckForRefineGraph": 64, "RefineIterations": 1,
               # beam: the fan-out shard servers must run the SAME
               # engine family the mesh walk runs — the single-chip
               # default (dense) would make the parity test compare
               # different algorithms (coincidentally equal only at
               # toy scale where dense covers everything)
               "MaxCheck": 128, "SearchMode": "beam"}
N, D = 256, 16          # divisible by every submesh we use: equal shards


def _corpus(n=N, d=D, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, d)).astype(np.float32)


from conftest import ServerThread as _ServerThread  # noqa: E402


@pytest.fixture(scope="module")
def mesh_built(tmp_path_factory):
    """One tiny 2-shard BKT mesh index, persisted (shard folders reused
    by the fan-out parity test and the load_index test)."""
    data = _corpus()
    folder = str(tmp_path_factory.mktemp("mesh_idx"))
    mesh = make_mesh(jax.devices()[:2])
    index = ShardedBKTIndex.build(data, DistCalcMethod.L2, mesh=mesh,
                                  params=TINY_PARAMS, save_to=folder)
    return data, index, folder


# ------------------------------------------------------------- compat shim

def test_shard_map_compat_shim(host_mesh):
    """parallel/_compat.py resolves a working shard_map on this JAX
    (the removed-`jax.shard_map` pre-existing failure class), and a
    sharded search actually runs through it."""
    from sptag_tpu.parallel import _compat

    assert callable(_compat.shard_map)
    data = _corpus(n=96, d=8, seed=1)
    idx = ShardedFlatIndex(data, DistCalcMethod.L2, base=1,
                           mesh=host_mesh(2))
    _, ids = idx.search(data[:3], k=1)
    np.testing.assert_array_equal(ids[:, 0], np.arange(3))


# ------------------------------------------- mesh-wide slot scheduler spine

def test_mesh_scheduler_matches_monolithic_ids(mesh_built):
    """The mesh-wide continuous-batching path (MeshGraphEngine under
    BeamSlotScheduler) returns the SAME ids as the monolithic mesh
    search at the same knobs (distances last-ulp-tolerant — the PR-4
    scheduler caveat), and the pools drain clean."""
    data, index, _ = mesh_built
    q = _corpus(n=12, seed=2)[:, :D]
    d_mono, i_mono = index.search(q, 5)
    sched = index.enable_continuous_batching(slots=64)
    futs = index.submit_batch(q, 5)
    from sptag_tpu.algo.scheduler import gather_futures

    d_cb, i_cb = gather_futures(futs, 5)
    np.testing.assert_array_equal(i_mono, i_cb)
    np.testing.assert_allclose(d_mono, d_cb, rtol=1e-5, atol=1e-6)
    st = sched.stats()
    assert st["live"] == 0 and st["pending"] == 0
    # shard-axis accounting: the scheduler published the mesh scope
    assert metrics.gauge_value("scheduler.mesh_shards") == 2.0
    assert metrics.counter_value("scheduler.shard_retired") >= 2 * len(q)


# ----------------------------------------------------- merge contract tests

def _fanout_merge(result, shard_of, n_local, k):
    """Host-side global merge of the aggregator's flat-concatenated
    per-shard lists — exactly what the reference leaves to clients, and
    the baseline the in-mesh collective merge must reproduce."""
    cand = []
    for r in result.results:
        s = shard_of[r.index_name]
        for vid, dist in zip(r.ids, r.dists):
            if vid >= 0:
                cand.append((float(dist), s * n_local + int(vid)))
    cand.sort(key=lambda t: t[0])          # stable: shard-major on ties
    out_i = np.full(k, -1, np.int64)
    out_d = np.full(k, np.inf, np.float64)
    for j, (dist, gid) in enumerate(cand[:k]):
        out_d[j], out_i[j] = dist, gid
    return out_d, out_i


def test_merge_contract_vs_socket_fanout(mesh_built):
    """Parity across k / MaxCheck: the one-dispatch in-mesh path returns
    bit-identical ids (distances within last-ulp tolerance) to the
    socket fan-out aggregator over the SAME shard contents — each shard
    server loads the exact sub-index folder the mesh was packed from."""
    from sptag_tpu.core.index import load_index
    from sptag_tpu.serve.aggregator import (
        AggregatorContext, AggregatorService, RemoteServer)

    data, index, folder = mesh_built
    n_local = index.n_local
    shard_of = {}
    shard_threads = []
    backends = []
    try:
        for s in range(2):
            ctx = ServiceContext(ServiceSettings(default_max_result=10))
            ctx.add_index(f"s{s}", load_index(f"{folder}/shard_{s:03d}"))
            shard_of[f"s{s}"] = s
            t = _ServerThread(SearchServer(ctx, batch_window_ms=1.0))
            t.start()
            shard_threads.append(t)
            backends.append(t.wait_ready())
        agg_ctx = AggregatorContext(search_timeout_s=20.0)
        agg_ctx.servers = [RemoteServer(h, p) for h, p in backends]
        tg = _ServerThread(AggregatorService(agg_ctx))
        tg.start()
        ha, pa = tg.wait_ready()
        try:
            client = AnnClient(ha, pa, timeout_s=20.0)
            client.connect()
            queries = _corpus(n=6, seed=3)
            for k, mc in ((3, 64), (10, 128)):
                for row in range(len(queries)):
                    # per-row dispatch on BOTH paths: the per-shard
                    # programs then run at identical (1, D) shapes, so
                    # the id contract is exact (a batched mesh dispatch
                    # against single-query servers could differ in the
                    # last ulp from XLA's batch-shape reduction tiling)
                    d_mesh, i_mesh = index.search(
                        queries[row:row + 1], k, max_check=mc)
                    qb = base64.b64encode(queries[row].tobytes()).decode()
                    res = client.search(
                        f"$resultnum:{k} $maxcheck:{mc} #{qb}")
                    assert res.status == wire.ResultStatus.Success
                    fd, fi = _fanout_merge(res, shard_of, n_local, k)
                    np.testing.assert_array_equal(
                        i_mesh[0], fi,
                        err_msg=f"k={k} mc={mc} row={row}")
                    real = i_mesh[0] >= 0
                    np.testing.assert_allclose(
                        d_mesh[0][real], fd[real], rtol=1e-5)
            client.close()
        finally:
            tg.stop()
    finally:
        for t in shard_threads:
            t.stop()


def test_merge_contract_deleted_mask(host_mesh):
    """Deleted-mask case over FLAT shards: the in-mesh tombstone filter
    agrees with per-shard deletes on the fan-out side — no deleted row
    surfaces, and the surviving ids match exactly."""
    import sptag_tpu as sp

    data = _corpus(n=128, d=8, seed=4)
    deleted = np.zeros(128, bool)
    deleted[[5, 70, 71, 100]] = True
    mesh = host_mesh(2)
    idx = ShardedFlatIndex(data, DistCalcMethod.L2, base=1, mesh=mesh,
                           deleted=deleted)
    n_local = idx.data.shape[0] // 2
    # fan-out baseline WITHOUT sockets: per-shard single-chip FLAT
    # indexes with the same rows deleted, host-merged like the
    # aggregator's client-side merge (the socket path itself is covered
    # above; this case isolates the tombstone semantics)
    per_shard = []
    for s in range(2):
        sub = sp.create_instance("FLAT", "Float")
        sub.set_parameter("DistCalcMethod", "L2")
        block = data[s * 64:(s + 1) * 64]
        sub.build(block)
        sub.delete(block[deleted[s * 64:(s + 1) * 64]])
        per_shard.append(sub)
    queries = data[[5, 20, 70, 100]]        # include deleted rows' vectors
    k = 6
    d_mesh, i_mesh = idx.search(queries, k)
    assert not set(np.flatnonzero(deleted)) & set(i_mesh.ravel())
    for row, q in enumerate(queries):
        cand = []
        for s, sub in enumerate(per_shard):
            dd, ii = sub.search_batch(q[None], k)
            for dist, vid in zip(dd[0], ii[0]):
                if vid >= 0:
                    cand.append((float(dist), s * n_local + int(vid)))
        cand.sort(key=lambda t: t[0])
        want = [gid for _, gid in cand[:k]]
        got = [gid for gid in i_mesh[row] if gid >= 0]
        assert got == want[:len(got)], (row, got, want)


# --------------------------------------------------- MeshServe serve tier

def test_mesh_serve_streaming_end_to_end(mesh_built):
    """[Service] MeshServe=1 over a mesh adapter: responses stream from
    the mesh-wide scheduler in retire order, the admission signals carry
    the mesh scope, and /healthz-visible mutation state reports the
    placement epoch."""
    data, index, _ = mesh_built
    ad = ServingAdapter(index, feature_dim=D)
    ctx = ServiceContext(ServiceSettings(default_max_result=5,
                                         mesh_serve=True))
    ctx.add_index("mesh", ad)
    server = SearchServer(ctx, batch_window_ms=1.0)
    t = _ServerThread(server)
    t.start()
    host, port = t.wait_ready()
    try:
        assert ad._mesh_serve                 # armed at server start
        client = AnnClient(host, port, timeout_s=20.0)
        client.connect()
        for j in (7, 100, 200):
            qb = base64.b64encode(data[j].tobytes()).decode()
            res = client.search(f"$resultnum:3 #{qb}")
            assert res.status == wire.ResultStatus.Success
            assert res.results[0].ids[0] == j
        client.close()
        assert metrics.counter_value("scheduler.retired") >= 3
        assert metrics.counter_value("server.streamed_responses") >= 1
        sig = server._admission_signals()
        assert sig["mesh_shards"] == 2.0
        health = server._healthz()
        assert health["indexes"]["mesh"]["mutation"]["mesh"]["shards"] == 2
    finally:
        t.stop()


def test_mesh_swap_epoch(mesh_built):
    """swap_impl publishes a whole mesh placement atomically: new
    queries see the new shards, the epoch advances, and the old
    placement's scheduler is retired (drains, never drops)."""
    data, index, _ = mesh_built
    ad = ServingAdapter(index, feature_dim=D)
    assert ad.enable_mesh_serve(slots=32)
    _, ids0 = ad.search_batch(data[:2], 1)
    np.testing.assert_array_equal(ids0[:, 0], [0, 1])
    data2 = _corpus(seed=9)
    index2 = ShardedBKTIndex.build(data2, DistCalcMethod.L2,
                                   mesh=index.mesh, params=TINY_PARAMS)
    old_sched = index._scheduler
    assert ad.swap_impl(index2) == 1
    assert index._scheduler is None and old_sched is not None
    assert index2._scheduler is not None      # MeshServe re-armed
    _, ids1 = ad.search_batch(data2[:2], 1)
    np.testing.assert_array_equal(ids1[:, 0], [0, 1])
    st = ad.mutation_state()
    assert st["epoch"] == 1 and st["swap_count"] == 1
    assert metrics.counter_value("mesh.swaps") == 1


def test_load_index_mesh_folder(mesh_built):
    """A folder with sharded.json loads as a ServingAdapter through the
    plain load_index path — the [Index_<name>] IndexFolder deployment
    story for in-mesh serving."""
    from sptag_tpu.core.index import load_index

    data, index, folder = mesh_built
    loaded = load_index(folder)
    assert isinstance(loaded, ServingAdapter)
    assert loaded.num_samples == N
    d_l, i_l = loaded.search_batch(data[:3], 2)
    d_m, i_m = index.search(data[:3], 2)
    np.testing.assert_array_equal(i_l, i_m)


def test_mesh_knobs(host_mesh):
    """MeshShardAxis sizes the shard axis at build; MeshKLocal caps the
    per-shard merge contribution (monolithic AND scheduler paths agree
    at the capped width); index-level MeshServe=1 arms the scheduler at
    placement time (the offline mirror of the [Service] setting)."""
    data = _corpus(n=128, d=8, seed=5)
    idx = ShardedBKTIndex.build(
        data, DistCalcMethod.L2,
        params=dict(TINY_PARAMS, MeshShardAxis=2, MeshKLocal=2,
                    MeshServe=1))
    assert idx.mesh.devices.size == 2
    assert int(idx.params.mesh_k_local) == 2
    assert idx._scheduler is not None      # armed by the index param
    q = data[:4]
    d5, i5 = idx.search(q, 5)
    # each shard contributes at most MeshKLocal=2 candidates: at most 4
    # real results per row, padded with -1 past that
    assert (i5[:, 4] == -1).all()
    assert ((i5[:, :4] >= 0).sum(axis=1) <= 4).all()
    from sptag_tpu.parallel.mesh_engine import MeshGraphEngine

    eng = MeshGraphEngine(idx)
    k_eff, L, B, T, limit = eng.walk_plan(5, 128)
    assert k_eff == 4                      # min(k, n, k_local * shards)
    # the scheduler path pads k_eff back out to the caller's k — the
    # streaming serve surface must honor the same (k,) row contract as
    # every synchronous path (MAX_DIST / -1 sentinels past k_eff)
    idx.enable_continuous_batching(slots=16)
    fd, fi = idx.submit_batch(q[:2], 5)[0].result()
    assert fd.shape == (5,) and fi.shape == (5,)
    assert fi[4] == -1


# -------------------------------------------------- off-parity golden bytes

def test_mesh_serve_off_parity_golden_bytes(mesh_built):
    """With MeshServe at its default (off), a server over a mesh adapter
    produces byte-identical wire responses to the reference layout and
    never builds a scheduler (the ci_check.sh standalone parity pass)."""
    data, index, _ = mesh_built
    # a FRESH adapter proves off means off (the module fixture's index
    # may carry a scheduler armed by the scheduler-parity test — the
    # ADAPTER path must not route to it with MeshServe off)
    ad = ServingAdapter(index, feature_dim=D)
    ctx = ServiceContext(ServiceSettings(default_max_result=5))
    ctx.add_index("mesh", ad)
    server = SearchServer(ctx, batch_window_ms=1.0)
    t = _ServerThread(server)
    t.start()
    host, port = t.wait_ready()
    try:
        assert not ad._mesh_serve
        qtext = "|".join(str(x) for x in data[7])
        expected_result = SearchExecutor(ctx).execute(qtext)
        expected_result.request_id = ""
        expected_body = expected_result.pack()
        expected = wire.PacketHeader(
            wire.PacketType.SearchResponse, wire.PacketProcessStatus.Ok,
            len(expected_body), 1, 77).pack() + expected_body

        body = wire.RemoteQuery(qtext).pack()
        s = socket.create_connection((host, port), timeout=10)
        s.sendall(wire.PacketHeader(
            wire.PacketType.SearchRequest, wire.PacketProcessStatus.Ok,
            len(body), 0, 77).pack() + body)
        s.settimeout(10)
        got = b""
        while len(got) < len(expected):
            chunk = s.recv(65536)
            if not chunk:
                break
            got += chunk
        s.close()
        assert got == expected
    finally:
        t.stop()
