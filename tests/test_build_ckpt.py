"""Resumable-build checkpoints (utils/build_ckpt.py).

The reference build restarts from scratch on any failure (BuildIndex,
reference src/Core/BKT/BKTIndex.cpp:279-306 — cheap on a local CPU).  The
TPU build's remote backend can die mid-build, so the pipeline checkpoints
each stage; these tests pin:

* a checkpointed build equals a plain build (same stages, same stream);
* an interrupted build resumes WITHOUT re-running completed stages;
* the checkpoint is fingerprint-bound (other data/params never match);
* a successful build clears its checkpoint subfolder.
"""

import os

import numpy as np
import pytest

import sptag_tpu as sp
from sptag_tpu.graph.rng import RelativeNeighborhoodGraph
from sptag_tpu.trees.bktree import BKTree
from sptag_tpu.utils.build_ckpt import BuildCheckpoint, build_fingerprint


def _mk_data(n=600, d=24, seed=3):
    rng = np.random.default_rng(seed)
    return rng.standard_normal((n, d)).astype(np.float32)


def _mk_index():
    index = sp.create_instance("BKT", "Float")
    index.set_parameter("DistCalcMethod", "L2")
    for k, v in (("BKTNumber", "1"), ("BKTKmeansK", "8"),
                 ("TPTNumber", "2"), ("TPTLeafSize", "64"),
                 ("NeighborhoodSize", "8"), ("CEF", "32"),
                 ("MaxCheckForRefineGraph", "64"), ("RefineIterations", "2"),
                 ("MaxCheck", "256")):
        index.set_parameter(k, v)
    return index


def test_checkpointed_build_matches_plain_build(tmp_path):
    data = _mk_data()
    plain = _mk_index()
    plain.build(data)
    ckpt = _mk_index()
    ckpt.build(data, checkpoint_dir=str(tmp_path / "ck"))
    assert np.array_equal(plain._graph.graph, ckpt._graph.graph)
    # success clears the fingerprint subfolder
    root = tmp_path / "ck"
    assert not any(p.is_dir() for p in root.iterdir()) \
        if root.exists() else True
    q = data[:5]
    dp, ip = plain.search_batch(q, 3)
    dc, ic = ckpt.search_batch(q, 3)
    assert np.array_equal(ip, ic)


def test_interrupted_build_resumes_completed_stages(tmp_path, monkeypatch):
    data = _mk_data()
    ck_dir = str(tmp_path / "ck")

    # interrupt the first build at the first refine (post-candidates) pass
    calls = {"n": 0}
    real_refine = RelativeNeighborhoodGraph.refine_once

    def dying_refine(self, *a, **kw):
        calls["n"] += 1
        raise RuntimeError("tunnel died")

    monkeypatch.setattr(RelativeNeighborhoodGraph, "refine_once",
                        dying_refine)
    first = _mk_index()
    with pytest.raises(RuntimeError):
        first.build(data, checkpoint_dir=ck_dir)
    assert calls["n"] == 1
    monkeypatch.setattr(RelativeNeighborhoodGraph, "refine_once",
                        real_refine)

    # stage files survived the crash: tree + candidates (the cheap
    # initial prune is recomputed on resume; refine passes checkpoint
    # after they complete — the crash was in the first one)
    sub = [p for p in (tmp_path / "ck").iterdir() if p.is_dir()]
    assert len(sub) == 1
    names = {p.name for p in sub[0].iterdir()}
    assert "tree.bin" in names
    assert "candidates.npz" in names

    # the resumed build must not re-run the tree stage nor any TPT tree's
    # all-pairs work (build_candidates itself runs again but serves every
    # tree from the checkpoint)
    def no_tree_build(self, *a, **kw):
        raise AssertionError("tree stage re-ran on resume")

    def no_tree_candidates(self, *a, **kw):
        raise AssertionError("TPT all-pairs re-ran on resume")

    monkeypatch.setattr(BKTree, "build", no_tree_build)
    monkeypatch.setattr(RelativeNeighborhoodGraph, "_tree_candidates",
                        no_tree_candidates)
    resumed = _mk_index()
    assert resumed.build(data, checkpoint_dir=ck_dir) == sp.ErrorCode.Success
    assert resumed.build_resumed
    monkeypatch.undo()

    # and its result equals an uninterrupted build's
    plain = _mk_index()
    plain.build(data)
    assert not plain.build_resumed
    assert np.array_equal(plain._graph.graph, resumed._graph.graph)
    dp, ip = plain.search_batch(data[:8], 5)
    dr, ir = resumed.search_batch(data[:8], 5)
    assert np.array_equal(ip, ir)


def test_kdt_interrupted_build_resumes(tmp_path, monkeypatch):
    """KDT inherits the resumable _build — its checkpointed tree must load
    back as a KDTree (KDTIndex overrides _load_tree), not a BKTree."""
    from sptag_tpu.trees.kdtree import KDTree

    data = _mk_data()
    ck_dir = str(tmp_path / "ck")
    index = sp.create_instance("KDT", "Float")
    index.set_parameter("DistCalcMethod", "L2")
    for k, v in (("KDTNumber", "1"), ("TPTNumber", "2"),
                 ("TPTLeafSize", "64"), ("NeighborhoodSize", "8"),
                 ("CEF", "32"), ("MaxCheckForRefineGraph", "64"),
                 ("RefineIterations", "2"), ("MaxCheck", "256")):
        index.set_parameter(k, v)

    real_refine = RelativeNeighborhoodGraph.refine_once

    def dying_refine(self, *a, **kw):
        raise RuntimeError("tunnel died")

    monkeypatch.setattr(RelativeNeighborhoodGraph, "refine_once",
                        dying_refine)
    with pytest.raises(RuntimeError):
        index.build(data, checkpoint_dir=ck_dir)
    monkeypatch.setattr(RelativeNeighborhoodGraph, "refine_once",
                        real_refine)

    def no_tree_build(self, *a, **kw):
        raise AssertionError("KDT tree stage re-ran on resume")

    monkeypatch.setattr(KDTree, "build", no_tree_build)
    resumed = sp.create_instance("KDT", "Float")
    resumed.set_parameter("DistCalcMethod", "L2")
    for k, v in (("KDTNumber", "1"), ("TPTNumber", "2"),
                 ("TPTLeafSize", "64"), ("NeighborhoodSize", "8"),
                 ("CEF", "32"), ("MaxCheckForRefineGraph", "64"),
                 ("RefineIterations", "2"), ("MaxCheck", "256")):
        resumed.set_parameter(k, v)
    assert resumed.build(data, checkpoint_dir=ck_dir) == sp.ErrorCode.Success
    assert resumed.build_resumed
    assert isinstance(resumed._tree, KDTree)
    _, ids = resumed.search_batch(data[:8], 5)
    assert (ids[:, 0] == np.arange(8)).all()


def test_fingerprint_binds_data_and_params(tmp_path):
    data = _mk_data()
    other = _mk_data(seed=4)
    assert build_fingerprint(data, "cfg") != build_fingerprint(other, "cfg")
    assert build_fingerprint(data, "cfg") != build_fingerprint(data, "cfg2")
    # distinct fingerprints key distinct subfolders -> no cross-talk
    a = BuildCheckpoint(str(tmp_path), build_fingerprint(data, "cfg"))
    b = BuildCheckpoint(str(tmp_path), build_fingerprint(other, "cfg"))
    a.put_bytes("tree", b"A")
    assert b.get_bytes("tree") is None
    assert a.get_bytes("tree") == b"A"
    assert a.resumed and not b.resumed


def test_corrupt_stage_file_is_ignored(tmp_path):
    ck = BuildCheckpoint(str(tmp_path), "f" * 40)
    ck.put_arrays("candidates", cand_ids=np.zeros((4, 2), np.int32),
                  cand_d=np.zeros((4, 2), np.float32),
                  trees_done=np.int64(1))
    path = os.path.join(ck.folder, "candidates.npz")
    with open(path, "wb") as f:
        f.write(b"not an npz")
    assert ck.get_arrays("candidates") is None


def test_gc_runs_only_on_clear_and_age_is_configurable(tmp_path,
                                                       monkeypatch):
    """Orphan GC (ADVICE r3): constructing a checkpoint must NOT reap
    stale siblings (a suspended build requeued late keeps its stages);
    GC runs from clear() — the single retire point — with an
    env-configurable age, and <= 0 disables it."""
    import time

    root = str(tmp_path)
    stale = os.path.join(root, "stalebuild")
    os.makedirs(stale)
    old = time.time() - 9 * 24 * 3600
    os.utime(stale, (old, old))

    # constructor leaves the stale sibling alone
    ck = BuildCheckpoint(root, "a" * 40)
    assert os.path.isdir(stale)

    # GC disabled: clear() keeps it too
    monkeypatch.setenv("SPTAG_TPU_BUILD_CKPT_GC_AGE_S", "0")
    ck.put_bytes("tree", b"x")
    ck.clear()
    assert os.path.isdir(stale)

    # configurable age: one hour -> the 9-day-old sibling is reaped,
    # a fresh sibling survives
    fresh = os.path.join(root, "freshbuild")
    os.makedirs(fresh)
    monkeypatch.setenv("SPTAG_TPU_BUILD_CKPT_GC_AGE_S", "3600")
    ck2 = BuildCheckpoint(root, "b" * 40)
    ck2.clear()
    assert not os.path.isdir(stale)
    assert os.path.isdir(fresh)


def test_sharded_build_keeps_checkpoints_until_all_shards_done(
        tmp_path, monkeypatch):
    """Multi-shard resume (round 4): a finished shard's checkpoint must
    survive until EVERY shard succeeds — per-shard clear-on-success made
    a death in shard s rebuild shards [0, s) from scratch.  Pin:
    keep_checkpoint=True defers the clear to the caller, the sharded
    build retires all checkpoints only at the end, and build_resumed
    aggregates the per-shard signals."""
    from sptag_tpu.core.types import DistCalcMethod
    from sptag_tpu.parallel.sharded import ShardedBKTIndex, make_mesh

    monkeypatch.setenv("SPTAG_TPU_BUILD_CKPT", str(tmp_path))
    data = _mk_data(n=400, d=16, seed=9)
    params = {"BKTNumber": 1, "BKTKmeansK": 8, "TPTNumber": 2,
              "TPTLeafSize": 64, "NeighborhoodSize": 8, "CEF": 24,
              "MaxCheckForRefineGraph": 64, "RefineIterations": 1,
              "MaxCheck": 128}

    # single-index keep_checkpoint contract
    idx = _mk_index()
    assert idx.build(data, keep_checkpoint=True) == sp.ErrorCode.Success
    ck = idx.last_checkpoint
    assert ck is not None and os.path.isdir(ck.folder)
    ck.clear()

    # sharded build: end state has NO leftover checkpoints (all retired
    # after success) and build_resumed False on a cold build
    index = ShardedBKTIndex.build(data, DistCalcMethod.L2,
                                  mesh=make_mesh(), params=params)
    assert index.build_resumed is False
    leftovers = [d for d in os.listdir(tmp_path)
                 if os.path.isdir(os.path.join(tmp_path, d))]
    assert leftovers == [], leftovers
