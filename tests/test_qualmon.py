"""Search-quality observatory (ISSUE 7): canonical recall math pinned
against hand-computed fixtures, estimator correctness on a planted
corpus, shadow-queue overflow/budget drop semantics (never blocks),
exact-scan oracle parity, index-health metrics, the aggregator+2-shard
end-to-end (gauge within its Wilson CI of offline truth, budget-starved
triage verdict + flight dump), [Service] ini plumbing / set_parameter
live-apply, and the QualitySampleRate=0 byte-parity / one-flag-test
contract (the ci_check.sh standalone pass)."""

import asyncio  # noqa: F401  (referenced via test_serve harness)
import json
import logging
import os
import socket
import threading
import time

import numpy as np
import pytest

import sptag_tpu as sp
from sptag_tpu.serve import wire
from sptag_tpu.serve.aggregator import (AggregatorContext,
                                        AggregatorService, RemoteServer)
from sptag_tpu.serve.server import SearchServer
from sptag_tpu.serve.service import (SearchExecutor, ServiceContext,
                                     ServiceSettings)
from sptag_tpu.utils import flightrec, metrics, qualmon

from tests.test_serve import _ServerThread


# ---------------------------------------------------------------------------
# canonical recall math (the one definition, hand-computed fixtures)
# ---------------------------------------------------------------------------

def test_recall_row_hand_computed_fixture():
    """Reference CalcRecall parity on a worked example: per truth slot,
    a hit is an id match — |{1}|/3 and |{4,5}|/3."""
    assert qualmon.recall_row([1, 2, 3], [1, 9, 8], 3) == \
        pytest.approx(1 / 3)
    assert qualmon.recall_row([4, 5, -1], [4, 5, 6], 3) == \
        pytest.approx(2 / 3)
    # padding on either side never counts; k bounds both lists — a
    # served id past position k is NOT a hit (it was not returned in
    # the top-k), and truth entries past k are not demanded
    assert qualmon.recall_row([-1, -1], [-1, -1], 2) == 0.0
    assert qualmon.recall_row([7, 1, 2], [7], 1) == 1.0
    assert qualmon.recall_row([1, 2, 3, 7], [7, 0, 9], 3) == 0.0
    assert qualmon.recall_row([7, 1, 2], [9, 7, 0], 3) == \
        pytest.approx(1 / 3)


def test_recall_row_distance_tie_handling():
    """The CalcRecall distance clause: a served id NOT in the truth set
    still hits when its distance equals a truth distance within
    tolerance — two distinct vectors tied at the same distance are
    equally correct answers (id 20 at dist 0.5 covers truth id 9)."""
    ids, dists = [1, 20, 3], [0.0, 0.5, 0.9]
    truth_ids, truth_dists = [1, 9, 8], [0.0, 0.5, 2.0]
    assert qualmon.recall_row(ids, truth_ids, 3) == pytest.approx(1 / 3)
    assert qualmon.recall_row(ids, truth_ids, 3, dists=dists,
                              truth_dists=truth_dists) == \
        pytest.approx(2 / 3)
    # tolerance is relative: 0.5 vs 0.5000001 matches, 0.5 vs 0.6 not
    assert qualmon.recall_row([20], [9], 1, dists=[0.5000001],
                              truth_dists=[0.5]) == 1.0
    assert qualmon.recall_row([20], [9], 1, dists=[0.6],
                              truth_dists=[0.5]) == 0.0


def test_recall_at_k_batch_and_container_shapes():
    """The bench/IndexSearcher surface: rows as ndarrays, truth as sets
    or lists — one definition for all consumers."""
    ids_all = np.array([[1, 2, 3], [4, 5, -1]])
    assert qualmon.recall_at_k(ids_all, [{1, 9, 8}, {4, 5, 6}], 3) == \
        pytest.approx(0.5)
    assert qualmon.recall_at_k(ids_all, np.array([[1, 9, 8], [4, 5, 6]]),
                               3) == pytest.approx(0.5)
    assert qualmon.recall_at_k([], [], 3) == 0.0


def test_wilson_interval():
    lo, hi = qualmon.wilson(50, 100)
    assert lo == pytest.approx(0.4038, abs=1e-3)
    assert hi == pytest.approx(0.5962, abs=1e-3)
    assert qualmon.wilson(0, 0) == (0.0, 1.0)
    lo, hi = qualmon.wilson(10, 10)
    assert lo > 0.6 and hi == 1.0
    lo, hi = qualmon.wilson(0, 10)
    assert lo == 0.0 and hi < 0.4


def test_dist_recall_greedy_match():
    """Distance-only recall (the aggregator merge check): greedy
    one-to-one matching with relative tolerance."""
    assert qualmon.dist_recall([0.1, 0.2, 0.3], [0.1, 0.2, 0.3], 3) == 1.0
    assert qualmon.dist_recall([0.1, 0.3], [0.1, 0.2], 2) == 0.5
    # one served 0.1 cannot cover two truth 0.1 slots
    assert qualmon.dist_recall([0.1, 5.0], [0.1, 0.1], 2) == 0.5


def test_bench_and_cli_delegate_to_qualmon():
    """The dedup satellite: both consumers call the single canonical
    function (monkeypatch-visible delegation)."""
    import bench
    from sptag_tpu.tools import index_searcher

    ids_all = np.array([[1, 2, 3], [4, 5, -1]])
    truth = [{1, 9, 8}, {4, 5, 6}]
    expect = qualmon.recall_at_k(ids_all, truth, 3)
    assert bench.recall_at_k(ids_all, truth, 3) == pytest.approx(expect)
    assert index_searcher.calc_recall(ids_all, truth, 3) == \
        pytest.approx(expect)


# ---------------------------------------------------------------------------
# estimator on a planted corpus (true recall known analytically)
# ---------------------------------------------------------------------------

@pytest.fixture()
def flat_corpus():
    rng = np.random.default_rng(1)
    data = rng.standard_normal((64, 6)).astype(np.float32)
    idx = sp.create_instance("FLAT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    idx.build(data)
    return idx, data


def test_estimator_planted_recall(flat_corpus):
    """Half the sampled queries serve their exact top-k, half serve
    garbage — the window recall is analytically 0.5 and the Wilson CI
    straddles it."""
    idx, data = flat_corpus
    qualmon.configure(sample_rate=1.0)
    k = 4
    for i in range(16):
        ex_d, ex_ids = idx.exact_search_batch(data[i], k)
        if i % 2 == 0:
            served = list(ex_ids[0])
        else:
            served = [-1] * k          # total miss
        r = qualmon.recall_row(served, ex_ids[0], k)
        qualmon.record_sample("flat", "main", r, k)
    agg = qualmon.aggregate_stats()
    assert agg["recall"] == pytest.approx(0.5)
    assert agg["lo"] < 0.5 < agg["hi"]
    assert agg["trials"] == 16 * k
    ws = qualmon.window_stats()["flat|main"]
    assert ws["samples"] == 16 and ws["recall"] == pytest.approx(0.5)
    # the labeled exposition carries the same numbers
    text = qualmon.render_prometheus()
    assert 'sptag_tpu_quality_recall_at_k{mode="flat",shard="main"} 0.5' \
        in text


def test_exact_oracle_ignores_approximations(flat_corpus):
    """The shadow oracle must be exact even when the index is configured
    to serve approximately — otherwise it would inherit the very error
    it is supposed to measure."""
    idx, data = flat_corpus
    dn = ((data[:5, None, :] - data[None, :, :]) ** 2).sum(-1)
    true = np.argsort(dn, axis=1)[:, :3].astype(np.int32)
    idx.set_parameter("SketchPrefilter", "1")
    idx.set_parameter("ApproxTopK", "1")
    _, ids = idx.exact_search_batch(data[:5], 3)
    assert np.array_equal(ids, true)


def test_exact_oracle_graph_index_and_deletes():
    """BKT/KDT run the oracle off the engine snapshot's resident arrays;
    deleted rows are excluded like search_batch."""
    rng = np.random.default_rng(2)
    data = rng.standard_normal((80, 6)).astype(np.float32)
    idx = sp.create_instance("BKT", "Float")
    for p, v in [("DistCalcMethod", "L2"), ("BKTKmeansK", "4"),
                 ("TPTNumber", "2"), ("TPTLeafSize", "16"),
                 ("NeighborhoodSize", "8"), ("CEF", "32"),
                 ("RefineIterations", "0")]:
        assert idx.set_parameter(p, v), p
    idx.build(data)
    try:
        _, ids = idx.exact_search_batch(data[:4], 1)
        assert list(ids[:, 0]) == [0, 1, 2, 3]
        idx.delete(data[:1])
        _, ids = idx.exact_search_batch(data[:1], 1)
        assert ids[0, 0] != 0
    finally:
        idx.close()


# ---------------------------------------------------------------------------
# shadow queue: overflow drops, budget drops — never blocks
# ---------------------------------------------------------------------------

def test_shadow_queue_overflow_drops_never_blocks():
    qualmon.configure(sample_rate=1.0, queue_cap=2)
    release = threading.Event()
    ran = []

    def slow_job():
        release.wait(5)
        ran.append(1)

    # first job may be picked up immediately; saturate queue + worker
    accepted = sum(qualmon.submit(slow_job) for _ in range(8))
    t0 = time.perf_counter()
    dropped = [qualmon.submit(slow_job) for _ in range(16)]
    elapsed = time.perf_counter() - t0
    assert elapsed < 0.5                 # drop path never blocks
    assert not all(dropped)
    c = qualmon.counters()
    assert c["queue_drops"] >= 1
    assert c["submitted"] == accepted + sum(dropped)
    release.set()
    assert qualmon.drain(10)
    assert len(ran) == c["submitted"]


def test_shadow_budget_drops_counted():
    """QualityShadowBudget bounds estimated device FLOPs: an oversized
    job is dropped and counted, zero-cost jobs still flow."""
    qualmon.configure(sample_rate=1.0, shadow_budget_gflops=0.001)
    big = 1e12                           # 1 TFLOP against a 1 MFLOP/s cap
    assert not qualmon.submit(lambda: None, est_flops=big)
    c = qualmon.counters()
    assert c["budget_drops"] == 1
    assert metrics.counter_value("quality.shadow_budget_drops") == 1
    assert qualmon.submit(lambda: None, est_flops=0.0)
    assert qualmon.drain(5)


def test_shadow_worker_error_is_counted_not_fatal():
    qualmon.configure(sample_rate=1.0)

    def bad():
        raise RuntimeError("boom")

    assert qualmon.submit(bad)
    assert qualmon.submit(lambda: qualmon.inc("after_error"))
    assert qualmon.drain(5)
    assert qualmon.counters()["shadow_errors"] == 1
    assert qualmon.snapshot()["quality_counters"]["after_error"] == 1


def test_sampling_rate_gate_deterministic():
    qualmon.configure(sample_rate=0.25)
    picks = [qualmon.maybe_sample() for _ in range(16)]
    assert sum(picks) == 4
    assert picks == [False, False, False, True] * 4


# ---------------------------------------------------------------------------
# triage classification
# ---------------------------------------------------------------------------

def test_classify_low_recall_verdicts():
    flightrec.note_query_stats("q-budget", iters=8, t_budget=8)
    code, detail = qualmon.classify_low_recall("q-budget", "beam")
    assert code == "beam_budget" and "beam terminated early" in detail
    flightrec.note_query_stats("q-early", iters=2, t_budget=8)
    assert qualmon.classify_low_recall("q-early", "beam")[0] == \
        "beam_converged_early"
    assert qualmon.classify_low_recall("none", "dense")[0] == \
        "dense_prefilter"
    assert qualmon.classify_low_recall("none", "flat", sketch=True)[0] == \
        "sketch_prefilter"
    assert qualmon.classify_low_recall("none", "flat")[0] == "unknown"
    # rids are client-supplied and reusable: a dense query sharing a rid
    # with an earlier budget-starved beam query must NOT inherit its
    # iteration counters (scheduler stats only apply to beam-capable
    # modes)
    assert qualmon.classify_low_recall("q-budget", "dense")[0] == \
        "dense_prefilter"


def test_note_query_stats_merges_producers():
    """The scheduler writes retire numbers; the quality monitor adds its
    verdict later — keys merge, neither producer erases the other."""
    flightrec.note_query_stats("rid-m", segments=3, iters=5, t_budget=8)
    flightrec.note_query_stats("rid-m", quality_recall=0.4,
                               quality_verdict="beam_budget")
    st = flightrec.query_stats("rid-m")
    assert st["segments"] == 3 and st["quality_verdict"] == "beam_budget"


def test_low_recall_sample_triages_and_dumps(tmp_path, caplog):
    """A sample below the floor: request-id-stamped warning with the
    verdict, stats merged under the rid, flight auto-dump written."""
    dump_dir = str(tmp_path / "dumps")
    flightrec.configure(enabled=True, dump_dir=dump_dir)
    qualmon.configure(sample_rate=1.0, recall_floor=0.9)
    flightrec.note_query_stats("rid-low", iters=4, t_budget=4)
    verdict, detail = qualmon.classify_low_recall("rid-low", "beam")
    with caplog.at_level(logging.WARNING, "sptag_tpu.utils.qualmon"):
        qualmon.record_sample("beam", "s0", 0.3, 10, rid="rid-low",
                              verdict=verdict, detail=detail)
    msgs = [r.getMessage() for r in caplog.records]
    assert any("low-recall query rid=rid-low" in m
               and "verdict=beam_budget" in m
               and "beam terminated early" in m for m in msgs), msgs
    st = flightrec.query_stats("rid-low")
    assert st["quality_verdict"] == "beam_budget"
    assert st["quality_recall"] == pytest.approx(0.3)
    dumps = [f for f in os.listdir(dump_dir) if f.endswith(".json")]
    assert dumps, "low-recall flight dump missing"
    with open(os.path.join(dump_dir, dumps[0])) as f:
        assert json.load(f)["otherData"]["reason"] == "low_recall"
    assert qualmon.counters()["low_recall"] == 1


# ---------------------------------------------------------------------------
# index health metrics
# ---------------------------------------------------------------------------

def test_graph_health_metrics():
    """Hand-checkable graph: 0->1->2 chain plus an isolated node 3;
    seeds at 0 reach {0,1,2} of 4 live nodes."""
    graph = np.array([[1, -1], [2, -1], [1, -1], [-1, -1]], np.int32)
    h = qualmon.graph_health(graph, None, np.array([0]))
    assert h["nodes"] == 4
    assert h["degree_min"] == 0 and h["degree_max"] == 1
    assert h["degree_hist"] == [1, 3, 0]     # one 0-degree, three 1-degree
    assert h["reachable_fraction"] == pytest.approx(0.75)
    # edges: 0->1 (1->0? no), 1->2 (2->1? yes), 2->1 (1->2? yes) -> 2/3
    assert h["reciprocal_fraction"] == pytest.approx(2 / 3, abs=1e-3)
    # deleting the isolated node makes the seeds cover every live node
    h2 = qualmon.graph_health(graph, np.array([0, 0, 0, 1], bool),
                              np.array([0]))
    assert h2["reachable_fraction"] == pytest.approx(1.0)
    assert h2["deleted_fraction"] == pytest.approx(0.25)


def test_index_health_published_on_mutation():
    rng = np.random.default_rng(3)
    data = rng.standard_normal((60, 6)).astype(np.float32)
    idx = sp.create_instance("BKT", "Float")
    for p, v in [("DistCalcMethod", "L2"), ("BKTKmeansK", "4"),
                 ("TPTNumber", "2"), ("TPTLeafSize", "16"),
                 ("NeighborhoodSize", "8"), ("CEF", "32"),
                 ("RefineIterations", "0")]:
        assert idx.set_parameter(p, v), p
    qualmon.configure(sample_rate=1.0)
    idx.build(data)
    try:
        idx.publish_quality_health(shard="shardX")
        h = qualmon.snapshot()["health"]["shardX"]
        for key in ("degree_hist", "reciprocal_fraction",
                    "reachable_fraction", "deleted_fraction", "samples"):
            assert key in h, key
        assert h["samples"] == 60 and h["deleted_fraction"] == 0.0
        # mutation republishes under the sticky label (on the shadow
        # worker — drain before reading)
        idx.delete(data[:1])
        assert qualmon.drain()
        h = qualmon.snapshot()["health"]["shardX"]
        assert h["deleted"] == 1
        assert h["deleted_fraction"] == pytest.approx(1 / 60, abs=1e-3)
        text = qualmon.render_prometheus()
        assert 'quality_graph_reachable_fraction{mode="",shard="shardX"}' \
            in text
    finally:
        idx.close()


def test_health_off_is_no_op():
    """With the monitor off, mutation-path health hooks publish nothing
    (the one-flag-test contract extends to build/add/delete)."""
    rng = np.random.default_rng(4)
    idx = sp.create_instance("FLAT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    idx.build(rng.standard_normal((16, 4)).astype(np.float32))
    assert qualmon.snapshot()["health"] == {}


# ---------------------------------------------------------------------------
# params: ini plumbing + set_parameter live-apply
# ---------------------------------------------------------------------------

def test_quality_params_ini_parity(tmp_path):
    ini = tmp_path / "svc.ini"
    ini.write_text("[Service]\nQualitySampleRate=0.25\n"
                   "QualityRecallFloor=0.8\nQualityShadowBudget=2.5\n"
                   "QualityWindow=128\n")
    s = ServiceContext.from_ini(str(ini)).settings
    assert s.quality_sample_rate == 0.25
    assert s.quality_recall_floor == 0.8
    assert s.quality_shadow_budget == 2.5
    assert s.quality_window == 128
    a = AggregatorContext.from_ini(str(ini))
    assert a.quality_sample_rate == 0.25
    assert a.quality_recall_floor == 0.8
    assert a.quality_shadow_budget == 2.5
    assert a.quality_window == 128
    # defaults: off
    ini2 = tmp_path / "empty.ini"
    ini2.write_text("[Service]\n")
    assert ServiceContext.from_ini(str(ini2)) \
        .settings.quality_sample_rate == 0.0
    assert AggregatorContext.from_ini(str(ini2)).quality_sample_rate == 0.0


def test_quality_params_live_apply_via_set_parameter():
    """The flight-recorder pattern: Index.QualitySampleRate etc. apply
    DIRECTLY to the process monitor on a warm index — both ways — and
    each knob maps to its own configure field."""
    idx = sp.create_instance("FLAT", "Float")
    assert not qualmon.enabled()
    assert idx.set_parameter("QualitySampleRate", "0.5")
    assert qualmon.enabled()
    assert idx.set_parameter("QualityRecallFloor", "0.75")
    assert qualmon.recall_floor() == 0.75
    assert idx.set_parameter("QualityWindow", "32")
    cfg = qualmon.snapshot()["config"]
    assert cfg == {"sample_rate": 0.5, "recall_floor": 0.75,
                   "shadow_budget_gflops": 0.0, "window": 32,
                   "queue_cap": qualmon.DEFAULT_QUEUE_CAP}
    assert idx.set_parameter("QualityShadowBudget", "1.5")
    assert qualmon.snapshot()["config"]["shadow_budget_gflops"] == 1.5
    assert idx.set_parameter("QualitySampleRate", "0")
    assert not qualmon.enabled()
    # BKT carries the same registry entries (INI save/load parity)
    bkt = sp.create_instance("BKT", "Float")
    assert bkt.get_parameter("QualitySampleRate") == "0"
    assert "QualitySampleRate=0" in bkt.params.save_config()


# ---------------------------------------------------------------------------
# end-to-end: aggregator over two shards
# ---------------------------------------------------------------------------

def _http_get(port, path):
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read().decode()
    conn.close()
    return resp.status, body


def _scrape_gauge(text, name):
    for line in text.splitlines():
        if line.startswith(name + " ") or line.startswith(name + "{"):
            return float(line.rsplit(" ", 1)[1])
    return None


@pytest.fixture(scope="module")
def beam_index():
    """Tiny continuous-batching BKT shared by the e2e test (the
    test_flightrec pattern — builds dominate suite cost)."""
    rng = np.random.default_rng(7)
    data = rng.standard_normal((120, 8)).astype(np.float32)
    idx = sp.create_instance("BKT", "Float")
    for p, v in [("DistCalcMethod", "L2"), ("BKTKmeansK", "4"),
                 ("TPTNumber", "2"), ("TPTLeafSize", "16"),
                 ("NeighborhoodSize", "8"), ("CEF", "32"),
                 ("RefineIterations", "0"), ("SearchMode", "beam"),
                 ("MaxCheck", "16"), ("BeamSegmentIters", "2"),
                 ("ContinuousBatching", "1")]:
        assert idx.set_parameter(p, v), p
    idx.build(data)
    idx.search_batch(data[:1], 3)
    yield idx, data
    idx.close()


def test_quality_e2e_aggregator_two_shards(beam_index, tmp_path):
    """THE acceptance loop: two shard servers + aggregator with
    QualitySampleRate=1 on a seeded corpus.  The scraped
    quality.recall_at_k gauge agrees with offline exact recall within
    its published Wilson CI; a deliberately budget-starved query
    (MaxCheck=16 -> T=1 walk iteration) lands a "beam terminated early"
    triage verdict on the request-id-stamped log and a flight dump; and
    /debug/quality serves windows + per-shard health on both tiers."""
    idx, data = beam_index
    dump_dir = str(tmp_path / "dumps")
    qset = dict(default_max_result=3, quality_sample_rate=1.0,
                quality_recall_floor=1.01)   # triage EVERY sample
    ctx_a = ServiceContext(ServiceSettings(**qset))
    ctx_a.add_index("shard_a", idx)
    ctx_b = ServiceContext(ServiceSettings(**qset))
    ctx_b.add_index("shard_b", idx)
    srv_a = SearchServer(ctx_a, batch_window_ms=1.0, metrics_port=-1,
                         flight_recorder=True, flight_dump_dir=dump_dir,
                         flight_tier="server_a")
    srv_b = SearchServer(ctx_b, batch_window_ms=1.0,
                         flight_recorder=True, flight_dump_dir=dump_dir,
                         flight_tier="server_b")
    ta, tb = _ServerThread(srv_a), _ServerThread(srv_b)
    ta.start()
    tb.start()
    (ha, pa), (hb, pb) = ta.wait_ready(60), tb.wait_ready(60)
    agg_ctx = AggregatorContext(search_timeout_s=30.0, metrics_port=-1,
                                merge_top_k=True,
                                quality_sample_rate=1.0,
                                quality_recall_floor=1.01)
    agg_ctx.servers = [RemoteServer(ha, pa), RemoteServer(hb, pb)]
    agg = AggregatorService(agg_ctx)
    tg = _ServerThread(agg)
    tg.start()
    hg, pg = tg.wait_ready(60)

    records = []

    class Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    qlog = logging.getLogger("sptag_tpu.utils.qualmon")
    capture = Capture()
    qlog.addHandler(capture)
    try:
        from sptag_tpu.serve.client import AnnClient

        client = AnnClient(hg, pg, timeout_s=30.0)
        client.connect()
        served = {}
        k = 3
        nq = 6
        for i in range(nq):
            rid = "qual-e2e-%03d" % i
            qtext = ("$indexname:shard_a,shard_b $maxcheck:16 "
                     + "|".join(str(x) for x in data[i]))
            res = client.search(qtext, request_id=rid)
            assert res.status == wire.ResultStatus.Success
            served[i] = res
        client.close()

        # every sampled query replays in the background; samples are
        # queued just AFTER each response hits the wire, so wait for
        # the expected submissions (2 shard replays + 1 merge check per
        # query), then for the shadow queue to drain
        deadline = time.time() + 30
        while time.time() < deadline and \
                qualmon.counters()["submitted"] < 3 * nq:
            time.sleep(0.05)
        assert qualmon.counters()["submitted"] >= 3 * nq, \
            qualmon.counters()
        assert qualmon.drain(30)

        # labeled gauge vs offline truth: the shard_a window must agree
        # with offline exact recall (served merged entries vs the exact
        # oracle — both shards serve the same index object) within its
        # published Wilson interval, and closely in value at rate=1.
        offline = []
        for i in range(nq):
            ex_d, ex_ids = idx.exact_search_batch(data[i], k)
            for r in served[i].results:
                if r.index_name != "shard_a":
                    continue
                offline.append(qualmon.recall_row(
                    [v for v in r.ids], ex_ids[0], k,
                    dists=[d for d in r.dists], truth_dists=ex_d[0]))
        assert len(offline) == nq
        status, text = _http_get(srv_a._metrics_http.port, "/metrics")
        assert status == 200
        lbl = '{mode="beam",shard="shard_a"}'
        g = _scrape_gauge(text, "sptag_tpu_quality_recall_at_k" + lbl)
        lo = _scrape_gauge(text, "sptag_tpu_quality_recall_at_k_lo" + lbl)
        hi = _scrape_gauge(text, "sptag_tpu_quality_recall_at_k_hi" + lbl)
        assert g is not None and lo is not None and hi is not None
        shard_mean = float(np.mean(offline))
        assert lo - 1e-9 <= shard_mean <= hi + 1e-9, (lo, shard_mean, hi)
        assert g == pytest.approx(shard_mean, abs=0.01)
        # the aggregate (unlabeled) gauge exists too and sits in [0, 1]
        agg_g = metrics.gauge("quality.recall_at_k").value
        assert 0.0 <= agg_g <= 1.0

        # budget-starved triage: MaxCheck=16 -> one walk iteration ->
        # iters == t_budget -> "beam terminated early" on the log
        deadline = time.time() + 10
        while time.time() < deadline and not any(
                "beam terminated early" in m for m in records):
            time.sleep(0.05)
        assert any("low-recall query rid=qual-e2e-" in m
                   and "verdict=beam_budget" in m
                   and "beam terminated early" in m
                   for m in records), records[:5]
        # ... and the flight dump rode along
        deadline = time.time() + 10
        dumps = []
        while time.time() < deadline and not dumps:
            dumps = ([f for f in os.listdir(dump_dir)
                      if f.endswith(".json")]
                     if os.path.isdir(dump_dir) else [])
            time.sleep(0.05)
        assert dumps, "no flight dump for low-recall queries"

        # /debug/quality on the shard tier: windows + per-shard health
        status, body = _http_get(srv_a._metrics_http.port,
                                 "/debug/quality")
        assert status == 200
        q = json.loads(body)
        assert q["enabled"] is True
        assert any(w["shard"] == "shard_a" for w in q["windows"].values())
        assert "shard_a" in q["health"]
        assert "reachable_fraction" in q["health"]["shard_a"]
        # aggregator tier (shared process): merged view includes both
        # shards' windows plus its own merge-agreement samples
        status, body = _http_get(agg._metrics_http.port, "/debug/quality")
        assert status == 200
        qa = json.loads(body)
        shards = {w["shard"] for w in qa["windows"].values()}
        assert {"shard_a", "shard_b"} <= shards
        assert "aggregator" in shards     # the merge check sampled too
    finally:
        qlog.removeHandler(capture)
        tg.stop()
        ta.stop()
        tb.stop()


# ---------------------------------------------------------------------------
# QualitySampleRate=0: byte parity + one flag test
# ---------------------------------------------------------------------------

def test_quality_off_parity_serve_bytes_and_zero_work():
    """With the monitor off (the default), the serve path produces
    byte-identical wire responses to the reference layout and performs
    no quality work — zero samples, zero threads, zero series (the
    ci_check.sh standalone parity pass, mirroring flightrec's)."""
    rng = np.random.default_rng(0)
    data = rng.standard_normal((50, 8)).astype(np.float32)
    index = sp.create_instance("FLAT", "Float")
    index.set_parameter("DistCalcMethod", "L2")
    index.build(data)
    ctx = ServiceContext(ServiceSettings(default_max_result=5))
    ctx.add_index("main", index)
    server = SearchServer(ctx, batch_window_ms=1.0)
    t = _ServerThread(server)
    t.start()
    host, port = t.wait_ready()
    try:
        assert not qualmon.enabled()
        qtext = "|".join(str(x) for x in data[7])
        expected_result = SearchExecutor(ctx).execute(qtext)
        expected_result.request_id = ""
        expected_body = expected_result.pack()
        expected = wire.PacketHeader(
            wire.PacketType.SearchResponse, wire.PacketProcessStatus.Ok,
            len(expected_body), 1, 77).pack() + expected_body

        body = wire.RemoteQuery(qtext).pack()
        s = socket.create_connection((host, port), timeout=10)
        s.sendall(wire.PacketHeader(
            wire.PacketType.SearchRequest, wire.PacketProcessStatus.Ok,
            len(body), 0, 77).pack() + body)
        s.settimeout(10)
        got = b""
        while len(got) < len(expected):
            chunk = s.recv(65536)
            if not chunk:
                break
            got += chunk
        s.close()
        assert got == expected
        assert qualmon.counters() == {
            "enabled": 0, "seen": 0, "sampled": 0, "submitted": 0,
            "queue_drops": 0, "budget_drops": 0, "shadow_errors": 0,
            "low_recall": 0, "shadow_gflops": 0.0}
        assert qualmon.render_prometheus() == ""
        assert qualmon.snapshot()["windows"] == {}
    finally:
        t.stop()
