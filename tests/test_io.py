"""Binary format round-trip tests against hand-computed reference layouts
(Dataset.h:144-158, BKTree.h:219-229, NeighborhoodGraph.h:376-386,
Labelset.h:47-52, MetadataSet.cpp:22-35)."""

import io
import os
import struct

import numpy as np

from sptag_tpu.core.vectorset import MetadataSet
from sptag_tpu.io import format as fmt
from sptag_tpu.utils.ini import IniReader


def test_matrix_layout_bytes():
    arr = np.array([[1, 2], [3, 4], [5, 6]], dtype=np.float32)
    buf = io.BytesIO()
    fmt.write_matrix(buf, arr)
    raw = buf.getvalue()
    # int32 rows, int32 cols, row-major payload
    assert struct.unpack_from("<ii", raw) == (3, 2)
    np.testing.assert_array_equal(
        np.frombuffer(raw[8:], np.float32).reshape(3, 2), arr)
    out = fmt.read_matrix(io.BytesIO(raw), np.float32)
    np.testing.assert_array_equal(out, arr)


def test_tree_forest_layout():
    starts = np.array([0, 7], np.int32)
    nodes = np.zeros(9, fmt.BKT_NODE_DTYPE)
    nodes["centerid"] = np.arange(9)
    nodes["childStart"] = np.arange(9) + 100
    nodes["childEnd"] = np.arange(9) + 200
    buf = io.BytesIO()
    fmt.write_tree_forest(buf, starts, nodes)
    raw = buf.getvalue()
    assert struct.unpack_from("<i", raw)[0] == 2          # treeNumber
    assert struct.unpack_from("<ii", raw, 4) == (0, 7)     # starts
    assert struct.unpack_from("<i", raw, 12)[0] == 9       # node count
    assert len(raw) == 16 + 9 * 12                         # 12-byte BKTNode
    s2, n2 = fmt.read_tree_forest(io.BytesIO(raw), fmt.BKT_NODE_DTYPE)
    np.testing.assert_array_equal(s2, starts)
    np.testing.assert_array_equal(n2, nodes)


def test_kdt_node_is_16_bytes():
    assert fmt.KDT_NODE_DTYPE.itemsize == 16
    nodes = np.zeros(3, fmt.KDT_NODE_DTYPE)
    nodes["split_value"] = [0.5, -1.25, 3.0]
    buf = io.BytesIO()
    fmt.write_tree_forest(buf, np.array([0], np.int32), nodes)
    _, n2 = fmt.read_tree_forest(io.BytesIO(buf.getvalue()),
                                 fmt.KDT_NODE_DTYPE)
    np.testing.assert_array_equal(n2["split_value"], nodes["split_value"])


def test_deletes_layout():
    mask = np.array([0, 1, 0, 1, 1], bool)
    buf = io.BytesIO()
    fmt.write_deletes(buf, mask)
    raw = buf.getvalue()
    assert struct.unpack_from("<i", raw)[0] == 3           # deleted count
    assert struct.unpack_from("<ii", raw, 4) == (5, 1)     # Dataset<int8> hdr
    out = fmt.read_deletes(io.BytesIO(raw))
    np.testing.assert_array_equal(out, mask)


def test_metadata_layout():
    metas = MetadataSet([b"alpha", b"", b"xy"])
    mbuf, ibuf = io.BytesIO(), io.BytesIO()
    metas.save(mbuf, ibuf)
    assert mbuf.getvalue() == b"alphaxy"
    raw = ibuf.getvalue()
    assert struct.unpack_from("<i", raw)[0] == 3
    offsets = np.frombuffer(raw, np.uint64, 4, 4)
    np.testing.assert_array_equal(offsets, [0, 5, 5, 7])
    loaded = MetadataSet.load(io.BytesIO(mbuf.getvalue()),
                              io.BytesIO(raw))
    assert [loaded.get_metadata(i) for i in range(3)] == [b"alpha", b"", b"xy"]


def test_ini_reader_case_insensitive():
    text = """
; comment
[Index]
IndexAlgoType=BKT
ValueType=Float

[MetaData]
MetaDataFilePath=metadata.bin
"""
    r = IniReader.loads(text)
    assert r.does_section_exist("index")
    assert r.get_parameter("INDEX", "indexalgotype") == "BKT"
    assert r.get_parameter("Index", "Missing", "dflt") == "dflt"
    assert r.section_items("Index")["IndexAlgoType"] == "BKT"
    r2 = IniReader.loads(r.dumps())
    assert r2.get_parameter("MetaData", "MetaDataFilePath") == "metadata.bin"


def test_save_over_existing_is_crash_safe(tmp_path):
    """Re-saving over an existing index folder must not corrupt it when the
    save dies midway: the swap happens only after every file is written."""
    import sptag_tpu as sp

    rng = np.random.default_rng(4)
    data = rng.standard_normal((300, 16)).astype(np.float32)
    idx = sp.create_instance("FLAT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    idx.build(data)
    folder = str(tmp_path / "idx")
    assert idx.save_index(folder) == sp.ErrorCode.Success

    # second save over the same folder succeeds and reloads
    idx.add(rng.standard_normal((5, 16)).astype(np.float32))
    assert idx.save_index(folder) == sp.ErrorCode.Success
    assert sp.load_index(folder).num_samples == 305

    # a save that dies midway leaves the previous checkpoint loadable
    orig = idx._save_index_data
    def boom(target):
        orig(target)
        raise RuntimeError("disk died")
    idx._save_index_data = boom
    try:
        idx.save_index(folder)
    except RuntimeError:
        pass
    loaded = sp.load_index(folder)
    assert loaded.num_samples == 305          # previous checkpoint intact
    _, ids = loaded.search_batch(data[:4], 1)
    assert (ids[:, 0] >= 0).all()


def test_load_recovers_interrupted_swap(tmp_path):
    """A crash between save_index's two renames leaves no directory at the
    target; load_index must recover from the staged/backup sibling."""
    import os
    import sptag_tpu as sp

    rng = np.random.default_rng(9)
    data = rng.standard_normal((200, 8)).astype(np.float32)
    idx = sp.create_instance("FLAT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    idx.build(data)
    folder = str(tmp_path / "idx")
    assert idx.save_index(folder) == sp.ErrorCode.Success

    # simulate the crash window: folder renamed away to .old-*, the fully
    # written staging dir left at .saving-*
    os.rename(folder, folder + ".old-123-456")
    import shutil
    shutil.copytree(folder + ".old-123-456", folder + ".saving-123-456")

    loaded = sp.load_index(folder)                  # recovers .saving first
    assert loaded.num_samples == 200
    assert os.path.exists(os.path.join(folder, "indexloader.ini"))

    # backup-only variant
    shutil.rmtree(folder)
    loaded = sp.load_index(folder)                  # falls back to .old-*
    assert loaded.num_samples == 200


def test_save_into_cross_filesystem_folder(tmp_path, monkeypatch):
    """A pre-created destination on a DIFFERENT filesystem than the
    staging sibling (container volume mountpoint): os.replace raises
    EXDEV and the save must fall back to copy2+fsync+unlink, still
    writing indexloader.ini last (ADVICE r5)."""
    import errno

    import sptag_tpu as sp
    from sptag_tpu.core import index as core_index

    rng = np.random.default_rng(11)
    data = rng.standard_normal((120, 12)).astype(np.float32)
    idx = sp.create_instance("FLAT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    idx.build(data)

    folder = tmp_path / "volume"
    folder.mkdir()                      # pre-created non-index folder

    real_replace = os.replace
    order = []

    def exdev_replace(src, dst):
        if ".saving-" in src:           # staging -> destination crossing
            raise OSError(errno.EXDEV, "Invalid cross-device link")
        order.append(os.path.basename(dst))
        return real_replace(src, dst)

    monkeypatch.setattr(core_index.os, "replace", exdev_replace)
    assert idx.save_index(str(folder)) == sp.ErrorCode.Success
    monkeypatch.undo()

    # the completeness sentinel landed LAST even on the fallback path
    assert order[-1] == "indexloader.ini"
    assert not any(n.endswith(".xdev-tmp") for n in os.listdir(folder))
    loaded = sp.load_index(str(folder))
    assert loaded.num_samples == 120
    _, ids = loaded.search_batch(data[:4], 1)
    assert (ids[:, 0] == np.arange(4)).all()


def test_overwrite_save_onto_mountpoint_falls_back(tmp_path, monkeypatch):
    """Second save onto a folder that can be neither renamed (EBUSY
    mountpoint) nor reached by rename from the staging sibling (EXDEV):
    the existing-index branch must degrade to the per-file move instead
    of crashing (code-review follow-up to the EXDEV satellite)."""
    import errno

    import sptag_tpu as sp
    from sptag_tpu.core import index as core_index

    rng = np.random.default_rng(12)
    data = rng.standard_normal((80, 10)).astype(np.float32)
    idx = sp.create_instance("FLAT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    idx.build(data)
    folder = str(tmp_path / "vol")
    assert idx.save_index(folder) == sp.ErrorCode.Success   # first save

    real_rename, real_replace = os.rename, os.replace

    def ebusy_rename(src, dst):
        if src.rstrip("/") == folder:
            raise OSError(errno.EBUSY, "Device or resource busy")
        return real_rename(src, dst)

    def exdev_replace(src, dst):
        if ".saving-" in src:
            raise OSError(errno.EXDEV, "Invalid cross-device link")
        return real_replace(src, dst)

    monkeypatch.setattr(core_index.os, "rename", ebusy_rename)
    monkeypatch.setattr(core_index.os, "replace", exdev_replace)
    idx.add(rng.standard_normal((7, 10)).astype(np.float32))
    assert idx.save_index(folder) == sp.ErrorCode.Success   # overwrite
    monkeypatch.undo()

    loaded = sp.load_index(folder)
    assert loaded.num_samples == 87
    _, ids = loaded.search_batch(data[:3], 1)
    assert (ids[:, 0] == np.arange(3)).all()
