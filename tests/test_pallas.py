"""Pallas probe-scoring kernel — interpreter-mode correctness on CPU.

The real kernel runs on TPU only (ops/pallas_kernels.py gates on platform);
interpreter mode executes the same kernel logic through the Pallas
interpreter so CI validates indexing/masking without a chip.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from sptag_tpu.ops import pallas_kernels


@pytest.fixture(autouse=True)
def _interpret_mode():
    pallas_kernels.set_interpret(True)
    yield
    pallas_kernels.set_interpret(False)


def test_probe_block_dots_matches_einsum():
    rng = np.random.default_rng(0)
    C, P, D, Q, nprobe = 7, 8, 128, 4, 3
    data_perm = jnp.asarray(rng.standard_normal((C, P, D)).astype(np.float32))
    queries = jnp.asarray(rng.standard_normal((Q, D)).astype(np.float32))
    topc = jnp.asarray(rng.integers(0, C, (Q, nprobe)).astype(np.int32))

    got = pallas_kernels.probe_block_dots(data_perm, queries, topc,
                                          interpret=True)
    want = jnp.einsum("qd,qjpd->qjp", queries, data_perm[topc])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-4)


def test_supported_gates():
    rng = np.random.default_rng(1)
    f32 = jnp.asarray(rng.standard_normal((4, 8, 128)).astype(np.float32))
    assert pallas_kernels.supported(f32)          # interpret mode is on
    i8 = jnp.asarray(rng.integers(-5, 5, (4, 32, 128)).astype(np.int8))
    assert pallas_kernels.supported(i8)           # int8: (32,128) tiles
    i8_bad = jnp.asarray(rng.integers(-5, 5, (4, 8, 128)).astype(np.int8))
    assert not pallas_kernels.supported(i8_bad)   # P not 32-multiple
    i16 = jnp.asarray(rng.integers(-5, 5, (4, 32, 128)).astype(np.int16))
    assert not pallas_kernels.supported(i16)      # int16 -> XLA fallback
    odd = jnp.asarray(rng.standard_normal((4, 8, 100)).astype(np.float32))
    assert not pallas_kernels.supported(odd)      # D not 128-multiple


def test_probe_block_dots_int8_exact():
    """int8 path must be the EXACT integer dot (int32 accumulation)."""
    rng = np.random.default_rng(4)
    C, P, D, Q, nprobe = 5, 32, 128, 3, 2
    data_perm = jnp.asarray(
        rng.integers(-127, 128, (C, P, D)).astype(np.int8))
    queries = jnp.asarray(rng.integers(-127, 128, (Q, D)).astype(np.int8))
    topc = jnp.asarray(rng.integers(0, C, (Q, nprobe)).astype(np.int32))

    got = pallas_kernels.probe_block_dots(data_perm, queries, topc,
                                          interpret=True)
    assert got.dtype == jnp.int32
    want = np.einsum("qd,qjpd->qjp",
                     np.asarray(queries, np.int64),
                     np.asarray(data_perm, np.int64)[np.asarray(topc)])
    np.testing.assert_array_equal(np.asarray(got, np.int64), want)


def test_dense_kernel_pallas_vs_xla_paths():
    """The full dense kernel must produce identical ids through both the
    Pallas and the XLA scoring paths."""
    from sptag_tpu.algo.dense import _dense_search_kernel

    rng = np.random.default_rng(2)
    C, P, D, Q, nprobe = 6, 16, 128, 8, 2
    n = C * P
    data = rng.standard_normal((n, D)).astype(np.float32)
    perm = data.reshape(C, P, D)
    mids = jnp.asarray(np.arange(n, dtype=np.int32).reshape(C, P))
    sq = jnp.asarray((data ** 2).sum(1).astype(np.float32).reshape(C, P))
    cents = jnp.asarray(perm.mean(axis=1))
    cent_sq = jnp.asarray((np.asarray(cents) ** 2).sum(1))
    deleted = jnp.zeros(n, bool)
    queries = jnp.asarray(rng.standard_normal((Q, D)).astype(np.float32))

    args = (jnp.asarray(perm), mids, sq, cents, cent_sq, deleted, queries,
            5, nprobe, 0, 1)
    d_x, i_x = _dense_search_kernel(*args, use_pallas=False)
    d_p, i_p = _dense_search_kernel(*args, use_pallas=True, interpret=True)
    np.testing.assert_array_equal(np.asarray(i_x), np.asarray(i_p))
    np.testing.assert_allclose(np.asarray(d_x), np.asarray(d_p),
                               rtol=1e-5, atol=1e-3)


def test_group_block_dots_matches_einsum():
    rng = np.random.default_rng(6)
    C, P, D, Q, G, U = 9, 8, 128, 16, 4, 5
    NG = Q // G
    data_perm = jnp.asarray(rng.standard_normal((C, P, D)).astype(np.float32))
    queries = jnp.asarray(rng.standard_normal((Q, D)).astype(np.float32))
    union = jnp.asarray(rng.integers(0, C, (NG, U)).astype(np.int32))

    got = pallas_kernels.group_block_dots(data_perm, queries, union,
                                          interpret=True)
    assert got.shape == (NG, U, G, P)
    want = jnp.einsum("gqd,gupd->guqp",
                      queries.reshape(NG, G, D), data_perm[union])
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-4)


def test_group_block_dots_int8_exact():
    rng = np.random.default_rng(7)
    C, P, D, Q, G, U = 5, 32, 128, 64, 32, 3
    NG = Q // G
    data_perm = jnp.asarray(
        rng.integers(-127, 128, (C, P, D)).astype(np.int8))
    queries = jnp.asarray(rng.integers(-127, 128, (Q, D)).astype(np.int8))
    union = jnp.asarray(rng.integers(0, C, (NG, U)).astype(np.int32))

    got = pallas_kernels.group_block_dots(data_perm, queries, union,
                                          interpret=True)
    assert got.dtype == jnp.int32
    want = np.einsum("gqd,gupd->guqp",
                     np.asarray(queries, np.int64).reshape(NG, G, D),
                     np.asarray(data_perm, np.int64)[np.asarray(union)])
    np.testing.assert_array_equal(np.asarray(got, np.int64), want)


def test_dense_grouped_kernel_pallas_vs_xla():
    """The grouped dense kernel must produce identical ids through both
    scoring paths."""
    from sptag_tpu.algo.dense import _dense_search_grouped_kernel

    rng = np.random.default_rng(8)
    C, P, D, Q, nprobe, G = 6, 16, 128, 16, 2, 4
    n = C * P
    data = rng.standard_normal((n, D)).astype(np.float32)
    perm = data.reshape(C, P, D)
    mids = jnp.asarray(np.arange(n, dtype=np.int32).reshape(C, P))
    sq = jnp.asarray((data ** 2).sum(1).astype(np.float32).reshape(C, P))
    cents = jnp.asarray(perm.mean(axis=1))
    cent_sq = jnp.asarray((np.asarray(cents) ** 2).sum(1))
    deleted = jnp.zeros(n, bool)
    queries = jnp.asarray(rng.standard_normal((Q, D)).astype(np.float32))

    args = (jnp.asarray(perm), mids, sq, cents, cent_sq, deleted, queries,
            jnp.int32(Q), 5, nprobe, 4, G, 0, 1)
    d_x, i_x = _dense_search_grouped_kernel(*args, use_pallas=False)
    d_p, i_p = _dense_search_grouped_kernel(*args, use_pallas=True,
                                            interpret=True)
    np.testing.assert_array_equal(np.asarray(i_x), np.asarray(i_p))
    np.testing.assert_allclose(np.asarray(d_x), np.asarray(d_p),
                               rtol=1e-5, atol=1e-3)


@pytest.mark.parametrize("metric,base", [(0, 127), (1, 127)])
def test_dense_kernel_int8_pallas_vs_xla(metric, base):
    """int8 metric composition (L2 qn+sq-2dot / cosine base^2-dot) must be
    identical through the Pallas int path and the XLA fallback."""
    from sptag_tpu.algo.dense import _dense_search_kernel

    rng = np.random.default_rng(5)
    C, P, D, Q, nprobe = 4, 32, 128, 8, 2
    n = C * P
    data = rng.integers(-127, 128, (n, D)).astype(np.int8)
    perm = data.reshape(C, P, D)
    mids = jnp.asarray(np.arange(n, dtype=np.int32).reshape(C, P))
    sq = jnp.asarray(
        (data.astype(np.float32) ** 2).sum(1).reshape(C, P))
    cents = jnp.asarray(perm.astype(np.float32).mean(axis=1))
    cent_sq = jnp.asarray((np.asarray(cents) ** 2).sum(1))
    deleted = jnp.zeros(n, bool)
    queries = jnp.asarray(rng.integers(-127, 128, (Q, D)).astype(np.int8))

    args = (jnp.asarray(perm), mids, sq, cents, cent_sq, deleted, queries,
            5, nprobe, metric, base)
    d_x, i_x = _dense_search_kernel(*args, use_pallas=False)
    d_p, i_p = _dense_search_kernel(*args, use_pallas=True, interpret=True)
    np.testing.assert_array_equal(np.asarray(i_x), np.asarray(i_p))
    np.testing.assert_allclose(np.asarray(d_x), np.asarray(d_p),
                               rtol=0, atol=0)   # both exact integer dots
