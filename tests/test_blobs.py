"""In-memory blob save/load (the embedding-host path) and FileMetadataSet.

Parity: VectorIndex::SaveIndex(config, blobs) / LoadIndex from blobs
(/root/reference/AnnService/src/Core/VectorIndex.cpp:126-158, :364-400) and
the lazy FileMetadataSet (inc/Core/MetadataSet.h:46)."""

import io
import os

import numpy as np
import pytest

import sptag_tpu as sp
from sptag_tpu.core.vectorset import FileMetadataSet, MetadataSet

PARAMS = [("DistCalcMethod", "L2"), ("BKTKmeansK", "8"), ("TPTNumber", "4"),
          ("TPTLeafSize", "128"), ("NeighborhoodSize", "16"), ("CEF", "64"),
          ("MaxCheckForRefineGraph", "128"), ("MaxCheck", "512"),
          ("RefineIterations", "1"), ("Samples", "100")]


def _build(algo="BKT", n=500, d=12, with_meta=True):
    rng = np.random.default_rng(1)
    data = rng.standard_normal((n, d)).astype(np.float32)
    index = sp.create_instance(algo, "Float")
    for name, value in PARAMS:
        index.set_parameter(name, value)
    meta = sp.MetadataSet(f"m{i}".encode() for i in range(n)) \
        if with_meta else None
    assert index.build(data, meta,
                       with_meta_index=with_meta) == sp.ErrorCode.Success
    return index, data


@pytest.mark.parametrize("algo", ["BKT", "FLAT"])
def test_blob_roundtrip_zero_filesystem(algo):
    index, data = _build(algo)
    config, blobs = index.save_index_blobs()
    assert isinstance(config, str) and "IndexAlgoType" in config
    assert all(isinstance(b, bytes) for b in blobs)

    loaded = sp.load_index_blobs(config, blobs)
    assert loaded.num_samples == index.num_samples
    assert loaded.metadata is not None
    assert loaded.metadata.get_metadata(7) == b"m7"
    d1, i1 = index.search_batch(data[:16], 5)
    d2, i2 = loaded.search_batch(data[:16], 5)
    np.testing.assert_array_equal(i1, i2)
    np.testing.assert_allclose(d1, d2, rtol=1e-5)
    # delete-by-metadata works through the rebuilt meta mapping
    assert loaded.delete_by_metadata(b"m3") == sp.ErrorCode.Success
    _, i3 = loaded.search_batch(data[3][None, :], 1)
    assert i3[0, 0] != 3


def test_blobs_byte_identical_to_folder_files(tmp_path):
    """Each blob must be byte-identical to the corresponding folder file —
    the two paths share one serializer."""
    index, _ = _build("BKT")
    config, blobs = index.save_index_blobs()
    folder = str(tmp_path / "idx")
    assert index.save_index(folder) == sp.ErrorCode.Success
    names = [name for name, _ in index._blob_writers()] + [
        "metadata.bin", "metadataIndex.bin"]
    assert len(blobs) == len(names)
    for name, blob in zip(names, blobs):
        with open(os.path.join(folder, name), "rb") as f:
            assert f.read() == blob, name


def test_blob_roundtrip_without_metadata():
    index, data = _build("BKT", with_meta=False)
    config, blobs = index.save_index_blobs()
    loaded = sp.load_index_blobs(config, blobs)
    assert loaded.metadata is None
    _, ids = loaded.search_batch(data[:4], 1)
    assert list(ids[:, 0]) == [0, 1, 2, 3]


def test_file_metadata_set_lazy(tmp_path):
    metas = MetadataSet([f"payload-{i}".encode() for i in range(100)])
    mp, ip = str(tmp_path / "metadata.bin"), str(tmp_path / "metaidx.bin")
    metas.save(mp, ip)

    fms = FileMetadataSet(mp, ip)
    assert fms.count == 100
    assert fms.get_metadata(0) == b"payload-0"
    assert fms.get_metadata(99) == b"payload-99"
    assert fms.get_metadata(100) == b""
    # lazy: no full-blob copy resident — only the offsets table
    assert not fms._metas
    assert fms._offsets.nbytes < os.path.getsize(mp)

    # staged adds merge on save
    fms.add(b"appended")
    assert fms.count == 101
    assert fms.get_metadata(100) == b"appended"
    mp2, ip2 = str(tmp_path / "m2.bin"), str(tmp_path / "i2.bin")
    fms.save(mp2, ip2)
    again = MetadataSet.load(mp2, ip2)
    assert again.count == 101
    assert again.get_metadata(50) == b"payload-50"
    assert again.get_metadata(100) == b"appended"
    fms.close()


def test_file_metadata_in_place_save(tmp_path):
    """Saving a FileMetadataSet over its own backing files must not
    truncate-before-read (the checkpoint-in-place round trip)."""
    metas = MetadataSet([f"v{i}".encode() for i in range(20)])
    mp, ip = str(tmp_path / "metadata.bin"), str(tmp_path / "metaidx.bin")
    metas.save(mp, ip)
    fms = FileMetadataSet(mp, ip)
    fms.add(b"new")
    fms.save(mp, ip)                       # same paths — in-place
    assert fms.get_metadata(3) == b"v3"    # still readable post-rewrite
    assert fms.get_metadata(20) == b"new"
    again = MetadataSet.load(mp, ip)
    assert again.count == 21
    assert [again.get_metadata(i) for i in (0, 19, 20)] == \
        [b"v0", b"v19", b"new"]
    fms.close()


def test_index_load_with_lazy_metadata(tmp_path):
    index, data = _build("BKT")
    folder = str(tmp_path / "idx")
    assert index.save_index(folder) == sp.ErrorCode.Success
    loaded = sp.load_index(folder, lazy_metadata=True)
    assert isinstance(loaded.metadata, FileMetadataSet)
    assert loaded.metadata.get_metadata(5) == b"m5"
    res = loaded.search(data[8], k=3, with_metadata=True)
    # metadata rides the lazy file reads and matches the returned ids
    assert res.metas == [b"m%d" % v for v in res.ids]
