"""Multi-host (DCN-path) sharded search: two REAL OS processes, 4 virtual
CPU devices each, one 8-device global mesh over gloo.

This is the test the reference never had (its distributed stack is
validated only manually, SURVEY.md §4): the multi-controller program built
by parallel/multihost.py must return well-formed, self-consistent results
and find exact self-matches across shard boundaries — including shards
owned by the OTHER process.
"""

import os
import pytest
import subprocess
import sys
import textwrap

import numpy as np

_WORKER = textwrap.dedent("""
    import sys
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    pid = int(sys.argv[1])
    port = sys.argv[2]
    nproc = int(sys.argv[3])
    from sptag_tpu.parallel import multihost
    multihost.initialize(f"localhost:{port}", num_processes=nproc,
                         process_id=pid)
    assert len(jax.devices()) == 8, jax.devices()
    from sptag_tpu.core.types import DistCalcMethod
    from sptag_tpu.parallel.sharded import make_mesh

    # every process derives the same corpus from the same seed; the loader
    # callback hands each shard only its rows (the multi-host contract)
    rng = np.random.default_rng(0)
    n, d = 1024, 24
    data = rng.standard_normal((n, d)).astype(np.float32)
    n_local = n // 8

    idx = multihost.build_process_sharded(
        lambda s: data[s * n_local:(s + 1) * n_local], n, d,
        DistCalcMethod.L2, mesh=make_mesh(), dense=True,
        params={"BKTNumber": 1, "BKTKmeansK": 4, "TPTNumber": 2,
                "TPTLeafSize": 32, "NeighborhoodSize": 8, "CEF": 16,
                "MaxCheckForRefineGraph": 64, "RefineIterations": 1,
                "MaxCheck": 128})

    # probe rows spread over ALL shards: every process must see exact
    # self-matches for rows whose shard lives on the other process too
    probes = np.arange(0, n, n_local // 2 + 3)
    dists, ids = idx.search(data[probes], k=3)
    assert dists.shape == (len(probes), 3) and ids.shape == dists.shape
    hits = (ids[:, 0] == probes).mean()
    assert hits >= 0.9, (hits, ids[:, 0], probes)
    assert np.all(np.diff(dists, axis=1) >= -1e-3)
    # the multi-chip dense mode over the same DCN mesh (geometry agreed
    # via the process_allgather host collective)
    dd, di = idx.search_dense(data[probes], k=3, max_check=256)
    dhits = (di[:, 0] == probes).mean()
    assert dhits >= 0.9, (dhits, di[:, 0], probes)
    print(f"proc {pid} OK hits={hits} dense={dhits}", flush=True)
""")


# tiered suite (ISSUE 6 satellite, VERDICT §7): multi-PROCESS mesh
# bring-up — minutes of jax.distributed startup per test; nightly tier
pytestmark = pytest.mark.slow

def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_mesh_procs(n_proc: int, devices_per_proc: int):
    env = dict(os.environ)
    env["XLA_FLAGS"] = (f"--xla_force_host_platform_device_count="
                        f"{devices_per_proc}")
    env.pop("JAX_PLATFORMS", None)    # worker forces cpu via jax.config
    port = str(_free_port())          # fixed ports collide across CI runs
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(i), port, str(n_proc)],
        env=env, cwd=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for i in range(n_proc)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            outs.append(out)
    finally:
        # one worker dying leaves its peers blocked in jax.distributed
        # initialize — never leak them past the test
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-2000:]}"
        assert f"proc {i} OK" in out, out[-2000:]


def test_two_process_mesh_search(tmp_path):
    _run_mesh_procs(2, 4)


def test_four_process_mesh_search(tmp_path):
    """4 controllers x 2 devices = the same 8-device global mesh: the
    geometry-agreement and per-process shard loading must be topology-
    independent (a real DCN deployment varies hosts-per-pod freely)."""
    _run_mesh_procs(4, 2)
