"""Runnable end-to-end mesh deployment example.

Builds a corpus-sharded BKT index over every available device (one shard
per chip; on a CPU-only host, set XLA_FLAGS=--xla_force_host_platform_device_count=8
to simulate a mesh), attaches frontend metadata, serves it through the
reference-compatible socket server, and queries it over the wire with the
per-request budget and metadata options.

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python docs/examples/mesh_serving.py

This is the TPU-native replacement for the reference's one-Server-per-
shard + Aggregator topology: the scatter/search/merge happens inside ONE
compiled program over ICI; the socket edge stays byte-compatible so
existing clients keep working (docs/MIGRATION.md).
"""

import asyncio
import base64
import threading
import time

import numpy as np


def main():
    import sptag_tpu as sp
    from sptag_tpu.core.types import DistCalcMethod
    from sptag_tpu.core.vectorset import MetadataSet
    from sptag_tpu.parallel.sharded import ServingAdapter, ShardedBKTIndex
    from sptag_tpu.serve import wire
    from sptag_tpu.serve.client import AnnClient
    from sptag_tpu.serve.server import SearchServer
    from sptag_tpu.serve.service import ServiceContext, ServiceSettings

    rng = np.random.default_rng(0)
    n, d = 8000, 64
    data = rng.standard_normal((n, d)).astype(np.float32)

    print("building mesh index over", len(__import__("jax").devices()),
          "devices ...")
    index = ShardedBKTIndex.build(
        data, DistCalcMethod.L2, dense=True,
        params={"BKTNumber": 1, "BKTKmeansK": 8, "TPTNumber": 4,
                "TPTLeafSize": 200, "NeighborhoodSize": 16, "CEF": 64,
                "MaxCheckForRefineGraph": 256, "RefineIterations": 1,
                "MaxCheck": 1024},
        metadata=MetadataSet(b"doc-%05d" % i for i in range(n)))

    # MeshServe (DESIGN.md §17): the server arms the mesh-wide
    # continuous-batching spine at start — responses stream from the
    # shard-spanning slot scheduler in retire order.  Drop the flag for
    # synchronous whole-batch serving (byte-identical wire responses).
    ctx = ServiceContext(ServiceSettings(default_max_result=10,
                                         mesh_serve=True))
    ctx.indexes["mesh"] = ServingAdapter(index, feature_dim=d)
    server = SearchServer(ctx, batch_window_ms=2.0)

    loop = asyncio.new_event_loop()
    addr = {}

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            addr["hp"] = await server.start("127.0.0.1", 0)
        loop.create_task(boot())
        loop.run_forever()

    threading.Thread(target=run, daemon=True).start()
    deadline = time.time() + 30
    while "hp" not in addr:
        if time.time() > deadline:
            raise RuntimeError("server failed to start within 30 s "
                               "(check the port/host and server logs)")
        time.sleep(0.05)
    host, port = addr["hp"]
    print(f"serving on {host}:{port}")

    client = AnnClient(host, port, timeout_s=30.0)
    client.connect()
    q = base64.b64encode(data[1234].tobytes()).decode()
    res = client.search(f"$resultnum:5 $extractmetadata:true "
                        f"$maxcheck:2048 #{q}")
    assert res.status == wire.ResultStatus.Success
    top = res.results[0]
    print("top-5 ids:", top.ids)
    print("top-1 metadata:", top.metas[0].decode())
    assert top.ids[0] == 1234 and top.metas[0] == b"doc-01234"
    client.close()
    # graceful teardown: stop the server inside its loop before stopping
    # the loop, so no task is destroyed while pending
    asyncio.run_coroutine_threadsafe(server.stop(), loop).result(timeout=5)
    loop.call_soon_threadsafe(loop.stop)
    time.sleep(0.2)
    print("OK — mesh search + metadata + per-request budget over the wire")


if __name__ == "__main__":
    main()
