"""Runnable quickstart — the notebook-free equivalent of the reference's
docs/examples/QuickstartGuide.ipynb flow: build an index with metadata,
search it, mutate it online, persist it, and query it over the wire.

    python docs/examples/quickstart.py          # from the repo root

Uses a small synthetic corpus so it finishes in ~a minute on any backend.
"""
import os
import sys
import threading

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

import sptag_tpu as sp  # noqa: E402


def main():
    rng = np.random.default_rng(0)
    n, d = 20_000, 64
    centers = rng.standard_normal((32, d)).astype(np.float32) * 4
    data = (centers[rng.integers(0, 32, n)]
            + rng.standard_normal((n, d)).astype(np.float32))

    # ---- build with metadata -------------------------------------------
    index = sp.create_instance("BKT", "Float")
    index.set_parameter("DistCalcMethod", "L2")
    for name, value in [("TPTNumber", "4"), ("CEF", "64"),
                        ("MaxCheckForRefineGraph", "256"),
                        ("RefineIterations", "1"), ("MaxCheck", "1024")]:
        index.set_parameter(name, value)
    metas = sp.MetadataSet(f"doc{i}".encode() for i in range(n))
    index.build(data, metas, with_meta_index=True)
    print(f"built BKT index over {n} vectors")

    # ---- search ---------------------------------------------------------
    res = index.search(data[42], k=5, with_metadata=True)
    print("top-5 for row 42:", res.ids[:5], "metas:", res.metas[:2])
    assert res.ids[0] == 42

    dists, ids = index.search_batch(data[:256], k=10)
    self_hits = float(np.mean(ids[:, 0] == np.arange(256)))
    print(f"batch of 256 queries: self-hit rate {self_hits:.3f}")

    # ---- online mutation ------------------------------------------------
    new_rows = data[:4] + 0.01
    index.add(new_rows, sp.MetadataSet(
        f"new{i}".encode() for i in range(4)))
    index.delete_by_metadata(b"doc7")
    res = index.search(data[7], k=3)
    assert 7 not in list(res.ids), "tombstoned row must not come back"
    print("online add + delete-by-metadata OK")

    # ---- persistence ----------------------------------------------------
    folder = "/tmp/quickstart_index"
    index.save_index(folder)
    index2 = sp.load_index(folder)
    res2 = index2.search(data[42], k=1)
    assert res2.ids[0] == 42
    print(f"saved to {folder} and reloaded; results match")

    # ---- serve over the wire -------------------------------------------
    import asyncio

    from sptag_tpu.serve.client import AnnClient
    from sptag_tpu.serve.server import SearchServer
    from sptag_tpu.serve.service import ServiceContext, ServiceSettings

    ctx = ServiceContext(ServiceSettings(default_max_result=5))
    ctx.add_index("quickstart", index2)
    server = SearchServer(ctx, batch_window_ms=1.0)
    addr = {}
    ready = threading.Event()
    loop = asyncio.new_event_loop()

    def serve():
        asyncio.set_event_loop(loop)

        async def boot():
            addr["hp"] = await server.start("127.0.0.1", 0)
            ready.set()
        loop.create_task(boot())
        loop.run_forever()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    ready.wait(10)
    host, port = addr["hp"]

    client = AnnClient(host, port)
    client.connect()
    qtext = "$extractmetadata:true " + "|".join(str(x) for x in data[42])
    reply = client.search(qtext)
    print("wire search:", reply.results[0].ids[:3],
          reply.results[0].metas[0])
    client.close()
    asyncio.run_coroutine_threadsafe(server.stop(), loop).result(5)
    loop.call_soon_threadsafe(loop.stop)
    print("quickstart complete")


if __name__ == "__main__":
    main()
