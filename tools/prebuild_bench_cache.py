"""Pre-build the bench's disk-cached indexes on the CPU backend.

The chip cold build is compile-dominated (~78 XLA shapes at 20-40 s each
through the tunnel — reports/BUILD_TIME.md), and round-5's observed tunnel
windows (~35 min) are shorter than one cold build.  Building the SAME
indexes here (CPU backend, local fast compiles) into `bench.build_or_load`'s
cache folders lets a recovered tunnel window spend its minutes on
measurement: the chip run then only compiles the search-side shapes.

The builders are bench.py's own (`build_headline_*`) so the cache keys AND
build semantics match by construction.  An exclusive flock serializes
concurrent invocations (tools/tpu_watch.py runs this as its stage 0; a
manual run may already hold the lock) and the resumable-build checkpoint
root is shared with bench so a build interrupted anywhere — including a
chip build killed by a tunnel death — resumes instead of restarting.
Safe to re-run; skips folders that already exist.
"""

import fcntl
import os
import shutil
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Force the CPU backend: the session env pins JAX_PLATFORMS=axon (the
# tunnel), and a CPU pre-build is this tool's whole point.  Env alone is
# not enough — sitecustomize imports jax at interpreter start, so the
# config must be re-pinned post-import (tests/conftest.py does the same);
# a dead tunnel otherwise hangs jax.devices() in the axon plugin's
# connect/backoff loop.
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import bench  # noqa: E402


def newest_mtime(path):
    """Newest mtime across a tree's CONTENTS (files and dirs), not just
    the top directory inode: writing a large blob INTO an already-created
    staging dir does not advance the dir's own mtime, so gating on it
    alone could rmtree a multi-hour save still in flight (ADVICE r5).
    Vanished entries (a concurrent save finishing its rename) are
    skipped; the top-level stat is the floor."""
    newest = os.path.getmtime(path)
    for dirpath, dirnames, filenames in os.walk(path):
        for name in dirnames + filenames:
            try:
                newest = max(newest,
                             os.path.getmtime(os.path.join(dirpath, name)))
            except OSError:
                pass
    return newest


def prebuild(tag, builder):
    if bench.cache_ready(tag):
        print(f"[prebuild] {tag}: cached already", flush=True)
        return
    t0 = time.time()
    index = builder()
    index.save_index(bench.cache_folder(tag))
    print(f"[prebuild] {tag}: built+saved in {time.time()-t0:.0f}s",
          flush=True)


def main() -> None:
    os.makedirs(bench.CACHE_DIR, exist_ok=True)
    # force, matching build_or_load (which overrides the env to this same
    # path): an inherited SPTAG_TPU_BUILD_CKPT pointing elsewhere would
    # hide the chip build's checkpoints and silently break cross-resume
    os.environ["SPTAG_TPU_BUILD_CKPT"] = os.path.join(
        bench.CACHE_DIR, "build_ckpt")
    lock = open(os.path.join(bench.CACHE_DIR, "prebuild.lock"), "w")
    fcntl.flock(lock, fcntl.LOCK_EX)      # blocks behind a running instance
    try:
        # sweep staging/backup orphans from killed saves (the staged
        # save_index leaves ~100 MB `.saving-*`/`.old-*` siblings when a
        # process dies mid-save — routine here: machine resets, stage
        # deadlines).  Age-gated so a save in flight right now is never
        # touched; under the flock so sweeps can't race each other.
        now = time.time()
        for name in os.listdir(bench.CACHE_DIR):
            if ".saving-" not in name and ".old-" not in name:
                continue
            path = os.path.join(bench.CACHE_DIR, name)
            try:
                if now - newest_mtime(path) > 3600:
                    shutil.rmtree(path, ignore_errors=True)
                    print(f"[prebuild] swept stale {name}", flush=True)
            except OSError:
                pass
        # specs ordered long-pole (200k f32) first so a partial run still
        # covers the headline index
        for tag, builder in bench.headline_build_specs():
            prebuild(tag, builder)
        print("[prebuild] done", flush=True)
    finally:
        fcntl.flock(lock, fcntl.LOCK_UN)
        lock.close()


if __name__ == "__main__":
    main()
