#!/usr/bin/env bash
# The whole local gate: graftlint (static) + tier-1 pytest (runtime).
# Mirrors what the driver runs; see docs/DESIGN.md §7.
#
#   tools/ci_check.sh                # lint + tier-1
#   tools/ci_check.sh --lint-only    # fast pre-commit check
set -euo pipefail

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO"

# the full suite includes the GL7xx lock-order pass, the GL8xx
# guarded-by pass and the GL9xx device-program contract pass;
# `--select GL7` / `--select GL8` / `--select GL9` scope a rerun
echo "== graftlint (GL1xx-GL9xx) =="
python -m tools.graftlint sptag_tpu/

if [[ "${1:-}" == "--lint-only" ]]; then
    exit 0
fi

# the ISSUE 4 correctness gate, standalone and first: the segmented walk
# (and the scheduler built on it) must return bit-identical results to
# the monolithic walk — if this fails, nothing else about the beam
# numbers means anything
echo "== beam segmented-vs-monolithic parity (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_beam_segmented.py -q \
    -p no:cacheprovider -k "parity or segment_param"

# the ISSUE 5 observability gate, standalone: with FlightRecorder=off
# (the default) the serve tier's wire bytes stay byte-identical to the
# reference layout and the hot path performs zero recorder work
echo "== flight recorder off: serve byte parity (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_flightrec.py -q \
    -p no:cacheprovider -k "off_parity"

# the ISSUE 7 observability gate, standalone: with QualitySampleRate=0
# (the default) the serve tier's wire bytes stay byte-identical and the
# hot path performs one flag test per query — the quality monitor's
# analog of the flight-recorder parity contract
echo "== quality monitor off: serve byte parity (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_qualmon.py -q \
    -p no:cacheprovider -k "off_parity"

# the ISSUE 7 lint gate, standalone: quality gauge/counter names passed
# to qualmon must be string literals (GL606, the GL6xx cardinality
# family) — a dynamic name would grow the labeled exposition unbounded
echo "== GL606 quality-name lint (standalone) =="
python -m tools.graftlint sptag_tpu/ --select GL606

# the ISSUE 8 robustness gate, standalone: with every overload-defense
# knob at its default (AdmissionControl off, DeadlineMs 0, HedgeBudget
# 0, FaultInject empty) the serve tier's wire bytes stay byte-identical
# to the reference layout and the defense path performs zero work
echo "== overload defense off: serve byte parity (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_admission.py -q \
    -p no:cacheprovider -k "off_parity"

# the ISSUE 8 lint gate, standalone: the overload-defense modules'
# metric/flight-event names are literals (GL601/602/603 extend to the
# new modules with no new baseline entries)
echo "== GL601/602/603 overload-defense names (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_lint.py -q \
    -p no:cacheprovider -k "issue8"

# the ISSUE 9 robustness gate, standalone: with every mutation knob at
# its default (WalEnabled 0, DeltaShardCapacity 0, AutoRefineThreshold
# 0) the serve tier's wire bytes stay byte-identical and the mutation
# subsystem performs zero work
echo "== mutation knobs off: serve byte parity (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_mutation.py -q \
    -p no:cacheprovider -k "off_parity"

# the ISSUE 9 recovery drill, standalone: every injected storage-fault/
# crash point (mid-WAL append, mid-snapshot blob, pre-rename,
# post-rename) yields a loadable index containing exactly the acked
# writes, checksums verified — if this fails, the durability contract
# is broken and no mutation feature on top of it matters
echo "== crash-recovery drill (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_mutation.py -q \
    -p no:cacheprovider -k "crash_matrix or manifest or wal"

# the ISSUE 9 lint gate, standalone: persistence writes in core//io
# ride the atomic-write/WAL helpers (GL411, zero baseline entries)
echo "== GL411 persistence-path lint (standalone) =="
python -m tools.graftlint sptag_tpu/ --select GL411

# the ISSUE 10 observability gate, standalone: with HostProfHz=0 (the
# default) the serve tier's wire bytes stay byte-identical, the sampler
# thread is never started and the stage pins are one flag test
echo "== host profiler off: serve byte parity (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_hostprof.py -q \
    -p no:cacheprovider -k "off_parity"

# the ISSUE 10 regression sentinel, self-tested: identical artifacts
# pass; a doctored 20% loadgen-p99 regression fails with a table naming
# the regressed metric — if this breaks, the perf gate is asleep
echo "== benchdiff self-test (identity + doctored regression) =="
python -m tools.benchdiff BENCH_r05.json BENCH_r05.json
python - <<'PYEOF'
import copy, json, os, subprocess, sys, tempfile
base = json.load(open("BENCH_r05.json"))
cur = copy.deepcopy(base)
broot = base["parsed"] if isinstance(base.get("parsed"), dict) else base
croot = cur["parsed"] if isinstance(cur.get("parsed"), dict) else cur
broot["loadgen"] = {"qps_at_slo": 512.0, "p50_ms": 20.0, "p99_ms": 100.0}
croot["loadgen"] = {"qps_at_slo": 512.0, "p50_ms": 20.0, "p99_ms": 120.0}
d = tempfile.mkdtemp()
bp, cp = os.path.join(d, "b.json"), os.path.join(d, "c.json")
json.dump(base, open(bp, "w")); json.dump(cur, open(cp, "w"))
r = subprocess.run([sys.executable, "-m", "tools.benchdiff", bp, cp],
                   capture_output=True, text=True)
assert r.returncode == 1, \
    f"doctored regression must exit nonzero: rc={r.returncode}\n{r.stdout}"
assert "loadgen.p99_ms" in r.stdout and "REGRESSED" in r.stdout, r.stdout
print("benchdiff self-test OK (doctored -20% p99 headroom fails)")
PYEOF

# the ISSUE 10 lint gate, standalone: host-profiler stage names are
# string literals (GL607, the GL6xx cardinality family)
echo "== GL607 hostprof-stage lint (standalone) =="
python -m tools.graftlint sptag_tpu/ --select GL607

# the ISSUE 11 serving gate, standalone: with MeshServe at its default
# (off) a server over a mesh adapter produces byte-identical wire
# responses and never builds a mesh scheduler; the same module holds
# the merge-contract parity (in-mesh ids == socket fan-out + host
# merge over identical shard contents)
echo "== mesh serve off: serve byte parity (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_mesh_serve.py -q \
    -p no:cacheprovider -k "off_parity"

# the ISSUE 12 lint gate, standalone: guarded-by inference (GL801-805
# fixed or justified, GL806 plain-lock migration) — an unguarded write
# to epoch-swapped serving state is the bug class every later roadmap
# item (autotuner, tiered pipeline) would otherwise ship
echo "== GL8 guarded-by / race lint (standalone) =="
python -m tools.graftlint sptag_tpu/ --select GL8

# the ISSUE 12 runtime gate, standalone: with RaceSanitizer off (the
# default) the tracked hot classes are completely untouched and the
# serve tier's wire bytes stay byte-identical
echo "== race sanitizer off: serve byte parity (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_racesan.py -q \
    -p no:cacheprovider -k "off_parity"

# the ISSUE 12 armed smoke: mutation + epoch-swap + scheduler tests
# under SPTAG_RACESAN=1 — the conftest per-test probe fails any test
# that observes a data race, so a green run IS racesan.races == 0; the
# static/runtime guard cross-check rides in test_racesan.py
echo "== racesan-armed smoke (mutate/swap/scheduler, races must be 0) =="
env JAX_PLATFORMS=cpu SPTAG_RACESAN=1 python -m pytest \
    tests/test_mutation.py tests/test_concurrent.py \
    tests/test_beam_segmented.py tests/test_racesan.py -q \
    -p no:cacheprovider -m 'not slow'

# the ISSUE 13 perf gate, standalone: with BinnedTopK at its default
# (off) every engine resolves bins=0 and compiles the byte-identical
# exact kernels, and a served response matches the reference wire
# layout; the same module holds the binned-on parity contracts
# (segmented/monolithic bit-parity, scheduler ids, mesh ids) and the
# recall-floor property tests of the bin-reduction primitive
echo "== binned top-k off: parity + golden bytes (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_binned_topk.py -q \
    -p no:cacheprovider -k "off_parity or parity"

# the ISSUE 14 capacity gate, standalone: with CascadeSearch at its
# default (off) no cascade state is ever built, FLAT results and served
# wire bytes stay byte-identical, and the parity contracts hold —
# host-tier fp re-rank bit-identical to device-resident, host-tier
# beam segmented/scheduler parity, mesh scheduler-vs-monolithic ids
echo "== cascade off: parity + golden bytes (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_cascade.py -q \
    -p no:cacheprovider -k "off_parity or parity"

# the ISSUE 14 lint gate, standalone: every new cascade/host-gather
# kernel is cost-model registered (GL605) with ZERO new baseline
# entries — a kernel outside the roofline ledger would make the
# capacity stage's %-of-peak and devmem numbers untrustworthy
echo "== GL605 cascade kernel coverage (standalone) =="
python -m tools.graftlint sptag_tpu/ --select GL605

# the ISSUE 15 observability gate, standalone: with the serving
# timeline, SLO engine and canary prober at their defaults (all off)
# the serve tier's wire bytes stay byte-identical, no sampler/prober
# thread exists and the timeline counters read zero
echo "== timeline/SLO/canary off: serve byte parity (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_timeline.py -q \
    -p no:cacheprovider -k "off_parity"

# the ISSUE 15 lint gate, standalone: timeline/SLO/canary series names
# are string literals (GL608, the GL6xx cardinality family) with ZERO
# baseline entries — a dynamic series name would grow the bounded
# time-series store without limit
echo "== GL608 timeline-series name lint (standalone) =="
python -m tools.graftlint sptag_tpu/ --select GL608

# the ISSUE 16 lint gate, standalone: the device-program contract pass
# (GL901 recompile hazards, GL902 hot-path transfers, GL903/904
# shard_map spec + collective axis contracts, GL905 never-assigned
# attribute reads with a ZERO-entry baseline, GL906 dead-telemetry
# handlers)
echo "== GL9 device-program contract lint (standalone) =="
python -m tools.graftlint sptag_tpu/ --select GL9

# the ISSUE 16 runtime gate, standalone: with TraceSanitizer off (the
# default outside the suite — SPTAG_TRACESAN= empty defeats conftest's
# suite-wide arming) jax's ArrayImpl readback dunders are untouched and
# the serve tier's wire bytes stay byte-identical
echo "== trace sentinel off: serve byte parity (standalone) =="
env JAX_PLATFORMS=cpu SPTAG_TRACESAN= python -m pytest \
    tests/test_tracesan.py -q -p no:cacheprovider -k "off_parity"

# the ISSUE 16 armed smoke: scheduler + mesh-serve + sentinel tests
# under SPTAG_TRACESAN=1 — the conftest per-test probe fails any test
# whose hot sections observed an implicit device->host transfer, so a
# green run IS tracesan.transfers == 0; the static/runtime contract
# cross-check rides in test_tracesan.py
echo "== tracesan-armed smoke (scheduler/mesh, transfers must be 0) =="
env JAX_PLATFORMS=cpu SPTAG_TRACESAN=1 python -m pytest \
    tests/test_beam_segmented.py tests/test_mesh_serve.py \
    tests/test_tracesan.py -q -p no:cacheprovider -m 'not slow'

# the ISSUE 17 serving gate, standalone: with Controller=0 and no
# AutotuneConfig (the defaults) the serve tier's wire bytes stay
# byte-identical, no controller object or audit entry exists and the
# decision counter reads zero — the closed loop is provably open when
# not asked for
echo "== controller off: serve byte parity (standalone) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_controller.py -q \
    -p no:cacheprovider -k "off_parity"

# the ISSUE 17 lint gate, standalone: controller decision-rule names
# passed to ctlaudit.record are string literals (GL609, the GL6xx
# cardinality family) with ZERO baseline entries — a dynamic rule name
# would make the bounded audit ring unsearchable
echo "== GL609 controller audit-rule lint (standalone) =="
python -m tools.graftlint sptag_tpu/ --select GL609

# the ISSUE 18 contract-graph gate, standalone: the GL10xx
# observability/config dataflow pass — every consumed metric/series/
# route/param has a producer (GL1001), every producer a consumer or a
# doc mention (GL1002), label sets agree (GL1003), params match
# docs/PARAMETERS.md (GL1004/1005), routes match EXPECTED_ROUTES
# (GL1006) — with ZERO baseline entries
echo "== GL10 observability contract graph (standalone) =="
python -m tools.graftlint sptag_tpu/ --select GL10

# the ISSUE 18 runtime gate, standalone: boot the armed server+
# aggregator scenario in-process, scrape /metrics + every debug route +
# the timeline, and diff the live exposition against the static
# ObsModel in BOTH directions — a name published but unmodeled, or
# modeled/consumed but never emitted, fails here
echo "== schema dump: live exposition vs static ObsModel =="
env JAX_PLATFORMS=cpu python -m tools.graftlint --schema-dump

# the ISSUE 6 observability gate, standalone: the cost ledger's
# registered FLOPs/bytes formulas for the flat, dense and beam-segment
# kernels must agree with XLA's own Compiled.cost_analysis() within
# ±15% on the CPU backend — if this fails, every roofline %-of-peak
# number the system publishes is untrustworthy
echo "== cost ledger vs XLA cost_analysis (standalone, CPU) =="
env JAX_PLATFORMS=cpu python -m pytest tests/test_costmodel.py -q \
    -p no:cacheprovider -k "crosscheck"

echo "== tier-1 pytest (CPU backend) =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider
