"""Recall experiment: grouped probing on the f32 headline corpus.

Round-2 measured grouped probing at union_factor=2 losing recall on the
loose 256-center f32 corpus (0.824 vs 0.967 ungrouped) and the bench has
run the f32 headline UNGROUPED since.  Ungrouped, the dense kernel's MXU
contraction is a (1, D) x (D, P) matvec — one systolic row busy out of
128.  The grouped kernel runs (G, D) x (D, P) per union block: G rows
busy, (Q/G)*U grid steps instead of Q*nprobe.  Whether the f32 corpus can
KEEP recall under grouping is a pure ranking question — platform
independent — so this experiment answers it on the CPU backend while the
union_factor=4 hypothesis (each query sees U*P candidates >= 4x MaxCheck,
recovering what the shared-union cut loses) waits on the chip only for
the QPS half of the story.

Usage: python tools/grouped_f32_recall.py [n] [nq]
Prints one JSON line per (G, U) config; appends to reports/GROUPED_F32.md.
"""

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    nq = int(sys.argv[2]) if len(sys.argv) > 2 else 2048
    import jax

    if os.environ.get("BENCH_PLATFORM") == "cpu":
        # the env var alone does not stop the pre-registered axon plugin
        # from initializing (and hanging on a down tunnel) — pin the list
        jax.config.update("jax_platforms", "cpu")
    import bench
    from sptag_tpu.utils import enable_compile_cache

    enable_compile_cache()
    data, queries = bench.make_dataset(n=n, nq=nq)
    truth = bench.l2_truth(data, queries, 10)

    index, build_s, cached = bench.build_or_load(
        f"bkt_f32_n{n}", lambda: bench.build_headline_f32(n, data),
        budget_s=1e9)
    print(json.dumps({"n": n, "nq": nq, "build_s": round(build_s, 1),
                      "cached": cached}), flush=True)

    rows = []
    for group, uf in [(0, 0), (8, 4), (16, 4), (32, 4), (16, 6), (32, 6)]:
        index.set_parameter("DenseQueryGroup", str(group))
        index.set_parameter("DenseUnionFactor", str(uf or 2))
        t0 = time.perf_counter()
        _, ids = index.search_batch(queries, 10)
        dt = time.perf_counter() - t0
        rec = bench.recall_at_k(ids, truth, 10)
        eff = getattr(index, "last_group_effective", None)
        try:
            eff = index._get_dense().last_effective_group
        except Exception:                                # noqa: BLE001
            pass
        row = {"group": group, "union_factor": uf, "recall_at_10":
               round(rec, 4), "effective_group": eff,
               "cpu_wall_s": round(dt, 1)}
        rows.append(row)
        print(json.dumps(row), flush=True)

    path = os.path.join(REPO, "reports", "GROUPED_F32.md")
    newfile = not os.path.exists(path)
    with open(path, "a") as f:
        if newfile:
            f.write(
                "# Grouped probing on the f32 headline corpus\n\n"
                "Recall is platform-independent (measured CPU); QPS "
                "columns get filled by the on-chip sweep.  MaxCheck 2048, "
                "k=10, corpus `bench.make_dataset`.\n\n"
                "| n | group | union_factor | effective G | recall@10 |\n"
                "|---|---|---|---|---|\n")
        for r in rows:
            f.write(f"| {n} | {r['group'] or 'off'} | "
                    f"{r['union_factor'] or '-'} | "
                    f"{r['effective_group']} | {r['recall_at_10']} |\n")


if __name__ == "__main__":
    main()
