"""benchdiff — the noise-aware perf-regression sentinel (ISSUE 10).

Five BENCH_r*.json snapshots sit in the repo root and until now nothing
machine-checked that a PR didn't regress QPS-at-SLO or %-of-peak — the
perf trajectory was tracked by hope.  This tool compares the CURRENT
bench artifact against a PINNED BASELINE artifact and exits nonzero with
a readable table when a watched metric regressed:

    python -m tools.benchdiff BENCH_r05.json BENCH_current.json
    python -m tools.benchdiff --json baseline.json current.json

Design decisions, in order of importance:

* **Noise-aware**: a metric regresses only when the relative change
  exceeds its threshold AND the absolute change exceeds its min-delta
  floor.  Bench numbers on a contended CI host jitter by several
  percent; the floors keep a 3-QPS wiggle on a 20-QPS beam stage from
  crying wolf, the relative thresholds keep a 500-QPS drop on a
  15k-QPS dense stage from hiding inside them.
* **Platform-gated**: an artifact measured on ``cpu`` is NOT comparable
  to one measured on ``tpu`` — throughput metrics are skipped with a
  visible note (recall and result-quality metrics still diff; the
  algorithm is platform-independent).
* **Schema-versioned**: artifacts stamp ``schema_version`` (bench.py);
  the sentinel diffs the INTERSECTION of watched keys present in both
  artifacts and prints both versions, so a baseline from an older
  schema degrades to fewer checks, never to a false alarm.
* **Direction-aware**: QPS/recall/%-of-peak regress DOWN, latency
  regresses UP; improvements are reported but never fail the gate.

Driver-wrapped artifacts (``{"parsed": {...}}``) unwrap automatically —
the same convention as tools/perf_report.py.  Exit codes: 0 pass,
1 regression, 2 usage/load error.  Wired into tools/ci_check.sh as a
self-test (identical artifacts must pass; a doctored −20 % loadgen p99
must fail); the intended PR gate is
``python -m tools.benchdiff BENCH_r<pinned>.json <fresh bench output>``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple

#: artifact schema this sentinel was written against (bench.py stamps
#: the same constant into new artifacts)
SCHEMA_VERSION = 1

HIGHER = "higher"     # regression = value went DOWN
LOWER = "lower"       # regression = value went UP


class Metric:
    """One watched key: dotted path, direction, relative threshold and
    absolute min-delta floor (both must be exceeded to flag), and
    whether the number depends on the measuring platform."""

    __slots__ = ("path", "direction", "rel", "floor", "platform_bound")

    def __init__(self, path: str, direction: str, rel: float,
                 floor: float, platform_bound: bool = True):
        self.path = path
        self.direction = direction
        self.rel = rel
        self.floor = floor
        self.platform_bound = platform_bound


#: the watched surface — per-stage throughput, latency, recall and
#: roofline %-of-peak.  Thresholds are deliberately loose (the bench
#: harness is single-run, not a statistics engine); tighten per-metric
#: as history accumulates rather than globally.
METRICS: List[Metric] = [
    # headline + per-stage throughput
    Metric("value", HIGHER, 0.15, 50.0),
    Metric("flat_qps", HIGHER, 0.15, 25.0),
    Metric("int8_qps", HIGHER, 0.15, 25.0),
    Metric("kdt_cosine_qps", HIGHER, 0.20, 10.0),
    Metric("kdt_dense_qps", HIGHER, 0.20, 25.0),
    Metric("beam_qps", HIGHER, 0.20, 2.0),
    # ISSUE 13: the binned walk's margin over the exact-top-k reference
    # pass measured in the SAME run — the bin-reduction specialization's
    # reason to exist.  Ratio of two same-run numbers, so it holds even
    # across host-speed changes that shift every absolute QPS.
    Metric("beam_binned_speedup", HIGHER, 0.20, 0.3),
    Metric("beam_exact_qps", HIGHER, 0.20, 2.0),
    # latency (lower is better)
    Metric("p50_batch_ms", LOWER, 0.20, 20.0),
    Metric("p99_batch_ms", LOWER, 0.20, 30.0),
    # result quality (platform-independent: the algorithm answered
    # worse, whatever measured it)
    Metric("recall_at_10", HIGHER, 0.01, 0.005, platform_bound=False),
    Metric("int8_recall_at_10", HIGHER, 0.01, 0.005,
           platform_bound=False),
    Metric("beam_recall_at_10", HIGHER, 0.01, 0.005,
           platform_bound=False),
    Metric("beam_exact_recall_at_10", HIGHER, 0.01, 0.005,
           platform_bound=False),
    Metric("kdt_cosine_recall_at_10", HIGHER, 0.01, 0.005,
           platform_bound=False),
    # open-loop serving capacity + tail (ISSUE 8's loadgen stage)
    Metric("loadgen.qps_at_slo", HIGHER, 0.20, 16.0),
    Metric("loadgen.p50_ms", LOWER, 0.20, 5.0),
    Metric("loadgen.p99_ms", LOWER, 0.20, 10.0),
    # ground-truth canary lines (ISSUE 15): exact recall vs the pinned
    # oracle truth is platform-independent — the canary answering worse
    # is a correctness regression whatever host measured it; canary p99
    # is the full-serve-path latency at probe (near-idle) load
    Metric("loadgen.canary_recall_at_10", HIGHER, 0.01, 0.005,
           platform_bound=False),
    Metric("loadgen.canary_p99_ms", LOWER, 0.25, 10.0),
    # offline-autotuner replay (ISSUE 17): the emitted config
    # artifact's operating point — QPS at the recall-SLO target and the
    # recall actually delivered there.  A worse chosen point means the
    # tuner (or the engine underneath it) regressed; recall is
    # platform-independent like every quality line.
    Metric("autotune.qps_at_slo", HIGHER, 0.20, 16.0),
    Metric("autotune.recall_at_10", HIGHER, 0.01, 0.005,
           platform_bound=False),
    # mutation-under-load stage (ISSUE 9).  GL1001: this pair was
    # silently dead from the day it landed — the stage emits
    # `steady_p99_ms` (this entry watched the transposed
    # `p99_steady_ms`) and emitted no read-throughput key at all
    # (bench.py now produces `read_qps`)
    Metric("mutate.read_qps", HIGHER, 0.20, 25.0),
    Metric("mutate.steady_p99_ms", LOWER, 0.25, 10.0),
    # in-mesh sharded serving stage (ISSUE 11): the one-dispatch mesh
    # path's throughput/tail, its margin over the socket fan-out
    # baseline, and the merged-path recall (platform-independent).  The
    # speedup ratio is the stage's reason to exist — hold that line.
    Metric("mesh_serve.inmesh_qps", HIGHER, 0.20, 8.0),
    Metric("mesh_serve.fanout_qps", HIGHER, 0.25, 5.0),
    Metric("mesh_serve.inmesh_p99_ms", LOWER, 0.25, 20.0),
    Metric("mesh_serve.speedup", HIGHER, 0.20, 0.15),
    Metric("mesh_serve.recall_at_10", HIGHER, 0.01, 0.005,
           platform_bound=False),
    # beyond-HBM tiered capacity (ISSUE 14): servable vectors per GB of
    # HBM at the recall floor (ledger-measured array bytes — platform-
    # independent), the chosen cascade config's recall line, and its
    # density ratio over the fp-only path (the stage's reason to exist)
    Metric("capacity.vectors_per_gb", HIGHER, 0.10, 1000.0,
           platform_bound=False),
    Metric("capacity.cascade_recall_at_10", HIGHER, 0.01, 0.005,
           platform_bound=False),
    Metric("capacity.capacity_ratio_vs_fp", HIGHER, 0.10, 0.3,
           platform_bound=False),
    # roofline %-of-peak per kernel family (ISSUE 6's ledger rows):
    # regressing the fraction of peak is the canary that a "faster in
    # QPS" change actually left device efficiency on the floor
    Metric("roofline.rows.flat.pct_peak", HIGHER, 0.20, 2.0),
    Metric("roofline.rows.dense.pct_peak", HIGHER, 0.20, 2.0),
    Metric("roofline.rows.beam.pct_peak", HIGHER, 0.20, 2.0),
    Metric("roofline.rows.int8.pct_peak", HIGHER, 0.20, 2.0),
]


def validate_catalog(metrics: Optional[List[Metric]] = None,
                     repo_root: str = ".") -> List[str]:
    """GL10xx startup contract: every catalog path's dotted segments
    must appear in the bench-artifact vocabulary (string constants in
    bench.py + the package) harvested by the observability graph —
    otherwise the entry can never match an artifact key and the diff
    silently skips it (how `mutate.p99_steady_ms` stayed dead).
    Returns human-readable problems; empty = valid.  Harvest failures
    (no bench.py next to the caller, no package tree) return [] — the
    static GL1001 pass owns that environment, not the CLI."""
    import os

    try:
        from tools.graftlint import obsgraph
        from tools.graftlint.core import Project
    except ImportError:
        return []
    pkg = os.path.join(repo_root, "sptag_tpu")
    if not os.path.isdir(pkg):
        return []
    model = obsgraph.build_model(Project.from_tree(pkg))
    if not model.has_bench_vocab:
        return []
    problems = []
    for metric in (METRICS if metrics is None else metrics):
        bad = obsgraph.unknown_catalog_segments(metric.path,
                                                model.bench_vocab)
        if bad:
            problems.append(
                f"catalog metric `{metric.path}`: segment(s) "
                f"{', '.join(repr(b) for b in bad)} unknown to any "
                "bench.py artifact key")
    return problems


def load_artifact(path: str) -> Dict[str, Any]:
    """Load one bench artifact, unwrapping the driver envelope
    (``{"parsed": {...}}``) like tools/perf_report.py does."""
    with open(path, encoding="utf-8") as f:
        obj = json.load(f)
    if not isinstance(obj, dict):
        raise ValueError(f"{path}: artifact is not a JSON object")
    if isinstance(obj.get("parsed"), dict):
        obj = obj["parsed"]
    return obj


def resolve(obj: Dict[str, Any], dotted: str) -> Optional[float]:
    """Walk a dotted path; returns a float or None when any hop is
    missing/None/non-numeric (missing keys are SKIPPED, not failed —
    stages are budget-gated and may legitimately be absent)."""
    cur: Any = obj
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


class Verdict:
    __slots__ = ("metric", "base", "cur", "delta_pct", "status", "note")

    def __init__(self, metric: Metric, base: float, cur: float,
                 status: str, note: str = ""):
        self.metric = metric
        self.base = base
        self.cur = cur
        self.delta_pct = ((cur - base) / abs(base) * 100.0
                          if base else float("inf") if cur else 0.0)
        self.status = status
        self.note = note


def judge(metric: Metric, base: float, cur: float) -> Verdict:
    delta = cur - base
    worse = -delta if metric.direction == HIGHER else delta
    rel = worse / abs(base) if base else (1.0 if worse > 0 else 0.0)
    # inclusive comparisons: a change AT the threshold counts — "a 20%
    # p99 regression fails a 20% gate" reads as operators expect
    if worse > 0 and rel >= metric.rel and worse >= metric.floor:
        return Verdict(metric, base, cur, "REGRESSED",
                       f"worse by {rel * 100.0:.1f}% "
                       f"(> {metric.rel * 100.0:.0f}% and "
                       f"> {metric.floor:g} abs)")
    if worse > 0:
        return Verdict(metric, base, cur, "ok",
                       "within noise thresholds")
    if worse < 0 and rel < -metric.rel and -worse > metric.floor:
        return Verdict(metric, base, cur, "improved", "")
    return Verdict(metric, base, cur, "ok", "")


#: per-stage compile-count lines (ISSUE 16): bench.py brackets each
#: `trace.span("bench.X")` stage with a recompile_guard.track_compiles
#: window, so the artifact's trace dict carries
#: `xla.backend_compile[bench.X]` spans whose COUNT is the number of
#: fresh XLA programs that stage minted.  More compiles in the same
#: stage is a recompile regression (a shape/dtype/static-arg started
#: varying — the GL901 hazard observed live) even when wall-clock QPS
#: hides it behind a warm cache.
_COMPILE_SPAN_PREFIX = "xla.backend_compile["


def _compile_count_metrics(baseline: Dict[str, Any],
                           current: Dict[str, Any]) -> List[Metric]:
    """Synthesize `<stage>.backend_compiles` metrics for every compile
    span labeled in BOTH artifacts (the watched list can't enumerate
    them statically — stages are budget-gated and labels grow with the
    bench)."""
    out: List[Metric] = []
    bt, ct = baseline.get("trace"), current.get("trace")
    if not isinstance(bt, dict) or not isinstance(ct, dict):
        return out
    for key in sorted(bt.keys() & ct.keys()):
        if not (key.startswith(_COMPILE_SPAN_PREFIX)
                and key.endswith("]")):
            continue
        label = key[len(_COMPILE_SPAN_PREFIX):-1]
        # direction-adjusted: compiles regress UPWARD; loose rel + a
        # 2-program floor absorbs warmup jitter (an extra dtype probe),
        # platform_bound because compile counts track the backend's
        # executable partitioning
        out.append(Metric(f"{label}.backend_compiles", LOWER, 0.25,
                          2.0, platform_bound=True))
    return out


def _resolve_compile_count(obj: Dict[str, Any], metric_path: str
                           ) -> Optional[float]:
    label = metric_path[:-len(".backend_compiles")]
    tr = obj.get("trace")
    if not isinstance(tr, dict):
        return None
    span = tr.get(f"{_COMPILE_SPAN_PREFIX}{label}]")
    if not isinstance(span, dict):
        return None
    count = span.get("count")
    if isinstance(count, bool) or not isinstance(count, (int, float)):
        return None
    return float(count)


def diff(baseline: Dict[str, Any], current: Dict[str, Any]
         ) -> Tuple[List[Verdict], List[str]]:
    """Judge every watched metric present in BOTH artifacts; returns
    (verdicts, notes).  Platform-bound metrics are skipped with a note
    when the two artifacts were measured on different backends."""
    notes: List[str] = []
    base_platform = baseline.get("platform", "")
    cur_platform = current.get("platform", "")
    platforms_differ = (base_platform and cur_platform
                        and base_platform != cur_platform)
    if platforms_differ:
        notes.append(
            f"platform mismatch (baseline={base_platform!r}, "
            f"current={cur_platform!r}): throughput/latency/roofline "
            "metrics skipped, quality metrics still checked")
    sv_base = baseline.get("schema_version", 0)
    sv_cur = current.get("schema_version", 0)
    if sv_base != sv_cur:
        notes.append(f"schema_version differs (baseline={sv_base}, "
                     f"current={sv_cur}): diffing shared keys only")
    verdicts: List[Verdict] = []
    for m in METRICS:
        if platforms_differ and m.platform_bound:
            continue
        base_v = resolve(baseline, m.path)
        cur_v = resolve(current, m.path)
        if base_v is None or cur_v is None:
            continue
        verdicts.append(judge(m, base_v, cur_v))
    for m in _compile_count_metrics(baseline, current):
        if platforms_differ and m.platform_bound:
            continue
        base_v = _resolve_compile_count(baseline, m.path)
        cur_v = _resolve_compile_count(current, m.path)
        if base_v is None or cur_v is None:
            continue
        verdicts.append(judge(m, base_v, cur_v))
    if not verdicts:
        notes.append("no watched metric present in both artifacts — "
                     "nothing was checked")
    return verdicts, notes


def render_table(verdicts: List[Verdict], notes: List[str],
                 baseline_path: str, current_path: str,
                 show_all: bool = False) -> str:
    lines = [f"benchdiff: {current_path} vs baseline {baseline_path}"]
    for n in notes:
        lines.append(f"  note: {n}")
    rows = [v for v in verdicts
            if show_all or v.status in ("REGRESSED", "improved")]
    if not rows and verdicts:
        lines.append(f"  {len(verdicts)} metric(s) checked, all within "
                     "thresholds")
    if rows:
        header = (f"  {'metric':<34} {'baseline':>12} {'current':>12} "
                  f"{'Δ%':>8}  status")
        lines.append(header)
        lines.append("  " + "-" * (len(header) - 2))
        for v in rows:
            lines.append(
                f"  {v.metric.path:<34} {v.base:>12.3f} {v.cur:>12.3f} "
                f"{v.delta_pct:>+8.1f}  {v.status}"
                + (f" — {v.note}" if v.note and v.status == "REGRESSED"
                   else ""))
    regressed = [v for v in verdicts if v.status == "REGRESSED"]
    lines.append(
        f"  verdict: {'FAIL — ' + str(len(regressed)) + ' regression(s)' if regressed else 'PASS'}"
        f" ({len(verdicts)} checked)")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.benchdiff",
        description="Compare a bench artifact against a pinned baseline "
                    "and fail on perf regressions.")
    parser.add_argument("baseline", help="pinned baseline artifact "
                        "(e.g. BENCH_r05.json)")
    parser.add_argument("current", help="freshly produced artifact")
    parser.add_argument("--json", action="store_true",
                        help="emit machine-readable verdicts instead of "
                        "the table")
    parser.add_argument("--show-all", action="store_true",
                        help="print every checked metric, not only "
                        "regressions/improvements")
    args = parser.parse_args(argv)
    problems = validate_catalog()
    if problems:
        for p in problems:
            print(f"benchdiff: {p}", file=sys.stderr)
        print("benchdiff: metric catalog does not match the bench "
              "artifact schema (config error)", file=sys.stderr)
        return 2
    try:
        baseline = load_artifact(args.baseline)
        current = load_artifact(args.current)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"benchdiff: cannot load artifacts: {e}", file=sys.stderr)
        return 2
    verdicts, notes = diff(baseline, current)
    if args.json:
        print(json.dumps({
            "baseline": args.baseline, "current": args.current,
            "schema_version": SCHEMA_VERSION,
            "notes": notes,
            "verdicts": [
                {"metric": v.metric.path, "baseline": v.base,
                 "current": v.cur,
                 "delta_pct": round(v.delta_pct, 3),
                 "status": v.status, "note": v.note}
                for v in verdicts],
            "pass": not any(v.status == "REGRESSED" for v in verdicts),
        }, indent=2))
    else:
        print(render_table(verdicts, notes, args.baseline, args.current,
                           show_all=args.show_all))
    return 1 if any(v.status == "REGRESSED" for v in verdicts) else 0


if __name__ == "__main__":
    raise SystemExit(main())
