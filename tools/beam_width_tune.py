"""BeamWidth ladder experiment: does a wider per-iteration pop close the
beam/dense throughput gap further?

The walk is overhead-bound, not bandwidth-bound (algo/engine.py module
docstring): its cost is the SERIAL iteration count T = ceil(MaxCheck/B)
times a fixed per-iteration cost.  `beam_width_for` auto-scales B as
MaxCheck/32 capped at 128 (round 4 — the ladder measured recall RISING
to B=256 on the 200k corpus, so the cap moved up from 64).  This tool
sweeps EXPLICIT BeamWidth values past the cap — an explicit value is a
floor the engine honors as-is — to measure where recall starts paying for
the extra width.  Counterpart knob in the reference: one node per pop,
always (/root/reference/AnnService/src/Core/BKT/BKTIndex.cpp:110-156);
width is a TPU-only degree of freedom.

Reuses the bench's cached 200k index (tag bkt_f32_n200000); run AFTER
bench.py has built it or the build cost is paid here.

Usage: python tools/beam_width_tune.py [n] [out_path]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200_000
    out_path = sys.argv[2] if len(sys.argv) > 2 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "reports", "BEAM_WIDTH.md")
    if os.environ.get("BENCH_PLATFORM") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from sptag_tpu.utils import enable_compile_cache

    enable_compile_cache()

    from bench import (make_dataset, l2_truth, build_or_load,
                       recall_at_k)

    k = 10
    nq = int(os.environ.get("BW_TUNE_NQ", "2048"))
    checks = tuple(int(c) for c in
                   os.environ.get("BW_TUNE_CHECKS", "2048,8192").split(","))
    widths = tuple(int(w) for w in
                   os.environ.get("BW_TUNE_WIDTHS", "0,64,128,256").split(","))
    data, queries = make_dataset(n=n, nq=nq)
    truth = l2_truth(data, queries, k)

    from bench import build_headline_f32

    index, build_s, cached = build_or_load(
        f"bkt_f32_n{n}", lambda: build_headline_f32(n, data), 1e9)
    index.set_parameter("SearchMode", "beam")
    dev = jax.devices()[0].platform

    lines = [
        "# BeamWidth ladder — beam-mode throughput vs width",
        "",
        f"Corpus n={n}, d=128, f32/L2; 2048 queries; recall@{k} vs exact "
        f"truth; platform={dev}; index cached={cached}.",
        "",
        "| MaxCheck | BeamWidth | packed | T iters | recall@10 | QPS |",
        "|---|---|---|---|---|---|",
    ]
    from sptag_tpu.algo.engine import beam_pool_size, beam_width_for
    packed_arms = ((0, 1) if os.environ.get("BW_TUNE_PACKED", "1") == "1"
                   else (0,))
    for max_check in checks:
        index.set_parameter("MaxCheck", str(max_check))
        for packed in packed_arms:
            # BeamPackedNeighbors (round 4): block-granular neighbor
            # gather; set_parameter invalidates the materialized engine
            index.set_parameter("BeamPackedNeighbors", str(packed))
            for bw in widths:
                # bw=0 row = the auto ladder (beam_width_for's choice)
                index.set_parameter("BeamWidth", str(bw if bw else 16))
                L = beam_pool_size(k, max_check, n)
                eff_b = beam_width_for(bw if bw else 16, max_check, L)
                t_iters = -(-max_check // eff_b)
                index.search_batch(queries, k)         # compile + warm
                best = float("inf")
                ids = None
                for _ in range(3):
                    t0 = time.perf_counter()
                    _, ids = index.search_batch(queries, k)
                    best = min(best, time.perf_counter() - t0)
                recall = recall_at_k(ids[:, :k], truth, k)
                lines.append(
                    f"| {max_check} | {'auto' if not bw else bw} "
                    f"({eff_b}) | {packed} | {t_iters} | {recall:.4f} | "
                    f"{len(queries) / best:,.0f} |")
                print(lines[-1], flush=True)
    with open(out_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
