"""On-chip dense-mode tuning sweep: grouped probing and batch depth.

The f32 headline has run UNGROUPED since round 2 (union_factor=2 lost
recall on the loose synthetic corpus: 0.824 vs 0.967).  Ungrouped, each
Pallas grid step contracts (1, D) x (D, P) — one MXU row busy.
`tools/grouped_f32_recall.py` measures (CPU, platform-independent)
whether union_factor=4 holds recall; THIS script measures the QPS half
on the chip, plus the other first-order lever: in-flight batch depth
(the tunnel costs ~60 ms per synced round trip, so QPS at fixed device
throughput rises with queries per call until device time dominates —
reports/TPU_PERF.md "tunnel latency effect").

Usage: python tools/dense_tune.py [n]
Appends measured rows to reports/GROUPED_F32.md and prints JSON lines.
"""

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    import jax

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200_000
    import bench
    from sptag_tpu.utils import enable_compile_cache

    enable_compile_cache()
    platform = jax.devices()[0].platform
    k = 10
    data, queries = bench.make_dataset(n=n, nq=4096)
    truth = bench.l2_truth(data, queries, k)

    index, build_s, cached = bench.build_or_load(
        f"bkt_f32_n{n}", lambda: bench.build_headline_f32(n, data),
        budget_s=1e9)
    rows = []
    # (group, union_factor, nq_in_flight): grouped configs first at the
    # bench's 4096, then batch-depth on the best-known ungrouped config
    for group, uf, nq in [(0, 0, 4096), (16, 4, 4096), (32, 4, 4096),
                          (32, 6, 4096), (0, 0, 2048), (0, 0, 8192),
                          (0, 0, 16384)]:
        qs = queries if nq <= len(queries) else np.concatenate(
            [queries] * (nq // len(queries)))[:nq]
        tr = truth if nq <= len(truth) else np.concatenate(
            [truth] * (nq // len(truth)))[:nq]
        index.set_parameter("DenseQueryGroup", str(group))
        index.set_parameter("DenseUnionFactor", str(uf or 2))
        index.search_batch(qs[:1024], k)            # compile small shape
        index.search_batch(qs, k)                   # compile + warm full
        t0 = time.perf_counter()
        reps = 3
        ids = None
        for _ in range(reps):
            _, out = index.search_batch(qs, k)
            ids = out if ids is None else ids
        dt = time.perf_counter() - t0
        qps = reps * nq / dt
        rec = bench.recall_at_k(ids, tr, k)
        try:
            eff = index._get_dense().last_effective_group
        except Exception:                            # noqa: BLE001
            eff = None
        row = {"platform": platform, "group": group, "union_factor": uf,
               "nq": nq, "qps": round(qps, 1),
               "recall_at_10": round(rec, 4), "effective_group": eff}
        rows.append(row)
        print(json.dumps(row), flush=True)

    with open(os.path.join(REPO, "reports", "GROUPED_F32.md"), "a") as f:
        f.write(f"\n## On-chip sweep ({platform}, n={n}, "
                f"{time.strftime('%Y-%m-%d')})\n\n"
                "| group | union_factor | effective G | nq in flight | QPS |"
                " recall@10 |\n|---|---|---|---|---|---|\n")
        for r in rows:
            f.write(f"| {r['group'] or 'off'} | {r['union_factor'] or '-'} "
                    f"| {r['effective_group']} | {r['nq']} | {r['qps']} | "
                    f"{r['recall_at_10']} |\n")


if __name__ == "__main__":
    main()
