"""One-off scale proof: 500k-row BKT build + search end-to-end on the CPU
backend (the TPU compile service was down when this ran; the CPU backend
executes the identical programs).  Results recorded in reports/SCALE.md.

Run from the repo root: `python tools/_scale_proof.py`
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax

    if os.environ.get("SCALE_PLATFORM", "cpu") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import sptag_tpu as sp
    from sptag_tpu.utils import enable_compile_cache, trace

    enable_compile_cache()
    n, d, nq = int(os.environ.get("SCALE_N", "500000")), 128, 1024
    rng = np.random.default_rng(17)
    centers = rng.standard_normal((512, d)).astype(np.float32) * 4.0
    data = (centers[rng.integers(0, 512, n)]
            + rng.standard_normal((n, d)).astype(np.float32))
    queries = (centers[rng.integers(0, 512, nq)]
               + rng.standard_normal((nq, d)).astype(np.float32))

    idx = sp.create_instance("BKT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    for name, value in [("TPTNumber", "8"), ("TPTLeafSize", "1000"),
                        ("NeighborhoodSize", "32"), ("CEF", "256"),
                        ("MaxCheckForRefineGraph", "512"),
                        ("RefineIterations", "2"), ("MaxCheck", "2048")]:
        idx.set_parameter(name, value)
    t0 = time.time()
    idx.build(data)
    build_s = time.time() - t0

    # exact truth in chunks (float64-free: f32 corpus, expanded form)
    dn = (data.astype(np.float64) ** 2).sum(1)
    truth = np.zeros((nq, 10), np.int64)
    for i in range(0, nq, 128):
        dd = dn[None, :] - 2.0 * (queries[i:i + 128].astype(np.float64)
                                  @ data.T.astype(np.float64))
        part = np.argpartition(dd, 10, axis=1)[:, :10]
        row = np.take_along_axis(dd, part, axis=1)
        truth[i:i + 128] = np.take_along_axis(part, np.argsort(row, axis=1),
                                              axis=1)

    idx.search_batch(queries[:64], 10)          # warm
    t0 = time.time()
    _, ids = idx.search_batch(queries, 10)
    dt = time.time() - t0
    rec = float(np.mean([len(set(ids[i]) & set(truth[i])) / 10
                         for i in range(nq)]))

    # persistence round trip at scale
    t0 = time.time()
    idx.save_index("/tmp/scale_idx")
    save_s = time.time() - t0
    t0 = time.time()
    idx2 = sp.load_index("/tmp/scale_idx")
    load_s = time.time() - t0
    _, ids2 = idx2.search_batch(queries[:64], 10)

    print(json.dumps({
        "n": n, "build_s": round(build_s, 1),
        "qps": round(nq / dt, 1), "recall_at_10": round(rec, 4),
        "save_s": round(save_s, 1), "load_s": round(load_s, 1),
        "loaded_matches": bool((ids2 == ids[:64]).all()),
        "trace": {k: round(v["total_s"], 1)
                  for k, v in trace.report().items()},
    }))


if __name__ == "__main__":
    main()
