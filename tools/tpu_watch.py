"""TPU recovery watcher: probe the tunneled backend, run the measurement
pipeline the moment it comes back.

The axon tunnel's failure modes (observed rounds 1-3; reports/TPU_PERF.md
"Caveat"): `jax.devices()` can block indefinitely, and the remote-compile
service can hang on NEW shapes while cached shapes keep executing.  Both
are transient — the backend has come back within tens of minutes each
time.  Chip time is the scarce resource of a round, so recovery must not
depend on a human noticing: this watcher probes in a SUBPROCESS with a
hard timeout every --interval seconds and, on the first healthy probe,
runs the measurement pipeline stages sequentially, each itself a
subprocess with a hard deadline so one hung stage cannot strand the rest.

Stages (in order of evidentiary value per minute of chip time):
  1. bench.py                      — the round's headline JSON line
  2. tools/baseline_configs.py     — BASELINE.md configs 1/2/4 at real shapes
  3. tools/sweep_modes.py          — beam-vs-dense MaxCheck curves

Each stage's stdout tail is appended to .bench_cache/watch_log.txt and the
bench line is copied to reports/bench_tpu_live.json for the round report.
The probe checks BOTH device init and a never-cached fresh-shape compile:
a backend that executes cached shapes but hangs new compiles would strand
stage 1 twenty minutes in (it happened in round 2; the probe shape is
randomized per run so it can never itself become cached).

Usage: python tools/tpu_watch.py [--interval 540] [--once] [--stages 1,2,3]
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)          # bench imports resolve from anywhere

import bench  # noqa: E402  (light import: numpy only, no jax)

CACHE = os.path.join(REPO, ".bench_cache")
LOG = os.path.join(CACHE, "watch_log.txt")


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    os.makedirs(CACHE, exist_ok=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s: float = 180.0) -> bool:
    """Healthy = devices init AND a LIVE fresh-shape compile both finish
    (snippet shared with bench.probe_accelerator — one probe semantic)."""
    code, env = bench.probe_snippet()
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s, env=env)
        if out.returncode == 0 and '"platform"' in out.stdout:
            info = json.loads(out.stdout.strip().splitlines()[-1])
            log(f"probe OK: platform={info['platform']}")
            return info["platform"] != "cpu"
        log(f"probe rc={out.returncode}: {out.stderr.strip()[-200:]}")
    except subprocess.TimeoutExpired:
        log(f"probe timed out after {timeout_s:.0f}s")
    except Exception as e:                               # noqa: BLE001
        log(f"probe error: {e!r}")
    return False


def run_stage(name: str, cmd, timeout_s: float, env=None) -> bool:
    log(f"stage {name}: {' '.join(cmd)} (deadline {timeout_s:.0f}s)")
    t0 = time.time()
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout_s, cwd=REPO,
                             env=dict(os.environ, **(env or {})))
        tail = (out.stdout.strip() or out.stderr.strip())[-2000:]
        log(f"stage {name} rc={out.returncode} in {time.time()-t0:.0f}s:\n"
            f"{tail}")
        if name in ("bench", "bench_cold"):
            # bench.py ALWAYS exits 0 with a JSON line (the driver contract)
            # — a tunnel death mid-run yields rc=0 with an "error" field.
            # Success for the pipeline = a clean line with a real value, so
            # a failed bench re-runs on the next healthy probe instead of
            # being marked done with a zero-QPS artifact.
            if out.returncode != 0:
                return False
            for line in reversed(out.stdout.strip().splitlines()):
                if line.startswith("{"):
                    try:
                        obj = json.loads(line)
                    except ValueError:
                        return False
                    # bench failure spellings: "error" (in-process),
                    # "child_error" (watchdog emitted a checkpointed
                    # partial), "tpu_child_error" (CPU-fallback line)
                    ok = (not any(obj.get(k) for k in
                                  ("error", "child_error",
                                   "tpu_child_error"))
                          and obj.get("value", 0) > 0
                          and obj.get("platform") != "cpu")
                    if ok:
                        # the cold re-run must not clobber the warm
                        # headline the round report reads — own artifact
                        dest = ("bench_tpu_cold.json" if name == "bench_cold"
                                else "bench_tpu_live.json")
                        with open(os.path.join(REPO, "reports", dest),
                                  "w") as f:
                            f.write(line + "\n")
                    return ok
            return False
        return out.returncode == 0
    except subprocess.TimeoutExpired:
        log(f"stage {name} exceeded {timeout_s:.0f}s — killed")
    except Exception as e:                               # noqa: BLE001
        log(f"stage {name} error: {e!r}")
    return False


def pipeline(stages, done) -> None:
    """Run the not-yet-succeeded stages in order; `done` collects names of
    stages that completed rc=0 so a mid-pipeline tunnel death resumes at
    the failed stage on the next healthy probe instead of exiting."""
    py = sys.executable
    plan = []

    # Round-5 change: the stages that consume the shared prebuilt index
    # cache run WARM (tools/prebuild_bench_cache.py populates it on CPU,
    # stage 0) — observed tunnel windows (~35 min) are shorter than one
    # compile-dominated cold build, so healthy windows must go to
    # measurement.  Gated on the cache folders actually existing (not
    # merely stage 0's rc): without them any of these stages would
    # silently cold-build the 200k index on chip and burn the window.
    # The true cold on-chip build_s is stage 8, LAST: worth one window,
    # not every window.  Every non-cold stage force-disables
    # BENCH_COLD_BUILD so an inherited =1 from a manual shell cannot
    # quietly bypass the cache (run_stage merges env over os.environ).
    warm = all(bench.cache_ready(t) for t, _ in bench.headline_build_specs())

    def w(env=None):
        return dict({"BENCH_COLD_BUILD": "0"}, **(env or {}))

    def gated(name):
        log(f"stage {name} deferred: index cache not fully prebuilt")

    if "1" in stages:
        if warm:
            plan.append(("bench", [py, "bench.py"], 5600,
                         w({"BENCH_BUDGET_S": "5400"})))
        else:
            gated("bench")
    if "2" in stages:
        plan.append(("baseline_configs",
                     [py, "tools/baseline_configs.py",
                      "--configs", "1,2,4"], 7200, w()))
    if "3" in stages:
        if warm:       # refine==0 run consumes the shared bkt_f32 tag
            plan.append(("sweep", [py, "tools/sweep_modes.py", "200000"],
                         3600, w()))
        else:
            gated("sweep")
        # second index at refine budget 2048 (own cache tag, chip-built):
        # beam recall with a production-quality graph (the 512-budget
        # default caps it)
        plan.append(("sweep_refine2048",
                     [py, "tools/sweep_modes.py", "200000"], 5400,
                     w({"SWEEP_REFINE_BUDGET": "2048"})))
    if "6" in stages:
        # verdict item 4 follow-up: where does recall pay for width?
        if warm:
            plan.append(("beam_width", [py, "tools/beam_width_tune.py",
                                        "200000"], 3600, w()))
        else:
            gated("beam_width")
    if "7" in stages:
        # round-5 item 2: strong-graph beam headline on chip — loads the
        # CPU-pre-built index when present (else builds on chip, far
        # faster than the CPU pre-build), then measures beam QPS/recall
        # at MaxCheck 2048/8192 on the real chip
        plan.append(("strong_beam",
                     [py, "tools/strong_beam_build.py", "200000"], 5400,
                     w({"STRONG_BEAM_PLATFORM": "tpu"})))
    if "4" in stages:
        if warm:
            plan.append(("dense_tune",
                         [py, "tools/dense_tune.py", "200000"], 3600, w()))
        else:
            gated("dense_tune")
    if "5" in stages:
        plan.append(("scale_rows", [py, "tools/deep1b_single_chip.py"],
                     7200, w()))
    if "8" in stages and "1" in stages and "bench" not in done:
        # gate logged so a --once run doesn't silently drop the stage:
        # the cold build unlocks on the pipeline pass AFTER the warm
        # headline lands (continuous mode reaches it; --once cannot)
        log("stage bench_cold deferred: warm bench has not completed yet")
    if "8" in stages and ("1" not in stages or "bench" in done):
        # true cold on-chip build_s (round-2 verdict ask) — bypasses the
        # index cache; the persistent XLA compile cache stays warm from
        # the earlier stages so this measures index construction, not the
        # tunnel's compile latency.  Gated behind the WARM bench: until
        # the headline line exists, no window may be spent on a cold
        # build that measurably does not fit in one.
        plan.append(("bench_cold", [py, "bench.py"], 5600,
                     {"BENCH_BUDGET_S": "5400", "BENCH_COLD_BUILD": "1"}))
    for name, cmd, deadline, env in plan:
        if name in done:
            continue
        if run_stage(name, cmd, deadline, env=env):
            done.add(name)
        elif not probe(60.0):
            # the backend died mid-pipeline — back to probing; this stage
            # and everything after it re-run on the next recovery
            log(f"backend unhealthy after stage {name}; pausing pipeline")
            return


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=540.0)
    ap.add_argument("--once", action="store_true",
                    help="single probe + pipeline attempt, no loop")
    ap.add_argument("--stages", default="1,2,3,8")
    args = ap.parse_args()
    stages = args.stages.split(",")
    # stage 0, unconditional and tunnel-independent: make sure the bench
    # index cache exists (CPU pre-build) BEFORE spending a tunnel window
    # on stage 1 — without it, stage 1 silently cold-builds on chip, the
    # exact failure the warm/cold stage split exists to prevent.  The
    # prebuild flock serializes with any manual run; when the cache is
    # already warm this returns in seconds.
    # Synchronous by design: on this 1-core box a background prebuild
    # would contend with any chip stage's host-side timing loop and
    # distort QPS; and stage 1 — the highest-value stage — needs the
    # cache anyway.  Hard deadline so a wedged lock-holder cannot strand
    # the probe loop; on timeout/failure the loop continues (and retries
    # stage 0 each round until it succeeds) — stage 1 would otherwise
    # burn every window on the compile-dominated chip cold build.  The
    # retry is cheap: the prebuild skips warm folders and resumes
    # partial builds from checkpoints.
    def ensure_cache() -> bool:
        log("stage 0: ensuring bench index cache (CPU pre-build)")
        t0 = time.time()
        try:
            rc = subprocess.run(
                [sys.executable, "tools/prebuild_bench_cache.py"],
                cwd=REPO, timeout=10800).returncode
        except subprocess.TimeoutExpired:
            rc = "timeout"
        log(f"stage 0 rc={rc} in {time.time()-t0:.0f}s"
            + ("" if rc == 0 else " — will retry next round"))
        return rc == 0

    cache_ok = ensure_cache()
    done = set()
    want = {"1": "bench", "2": "baseline_configs", "4": "dense_tune",
            "5": "scale_rows", "6": "beam_width", "7": "strong_beam",
            "8": "bench_cold"}
    total = len([s for s in stages if s in want]) + \
        (2 if "3" in stages else 0)
    while True:
        if not cache_ok:
            cache_ok = ensure_cache()
        if probe():
            pipeline(stages, done)
            if len(done) >= total:
                log(f"pipeline complete ({sorted(done)}); exiting")
                return
            log(f"stages done so far: {sorted(done)}")
        if args.once:
            return
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
