"""TPU recovery watcher: probe the tunneled backend, run the measurement
pipeline the moment it comes back.

The axon tunnel's failure modes (observed rounds 1-3; reports/TPU_PERF.md
"Caveat"): `jax.devices()` can block indefinitely, and the remote-compile
service can hang on NEW shapes while cached shapes keep executing.  Both
are transient — the backend has come back within tens of minutes each
time.  Chip time is the scarce resource of a round, so recovery must not
depend on a human noticing: this watcher probes in a SUBPROCESS with a
hard timeout every --interval seconds and, on the first healthy probe,
runs the measurement pipeline stages sequentially, each itself a
subprocess with a hard deadline so one hung stage cannot strand the rest.

Stages (in order of evidentiary value per minute of chip time):
  1. bench.py                      — the round's headline JSON line
  2. tools/baseline_configs.py     — BASELINE.md configs 1/2/4 at real shapes
  3. tools/sweep_modes.py          — beam-vs-dense MaxCheck curves

Each stage's stdout tail is appended to .bench_cache/watch_log.txt and the
bench line is copied to reports/bench_tpu_live.json for the round report.
The probe checks BOTH device init and a never-cached fresh-shape compile:
a backend that executes cached shapes but hangs new compiles would strand
stage 1 twenty minutes in (it happened in round 2; the probe shape is
randomized per run so it can never itself become cached).

Usage: python tools/tpu_watch.py [--interval 540] [--once] [--stages 1,2,3]
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CACHE = os.path.join(REPO, ".bench_cache")
LOG = os.path.join(CACHE, "watch_log.txt")


def log(msg: str) -> None:
    line = f"[{time.strftime('%H:%M:%S')}] {msg}"
    print(line, flush=True)
    os.makedirs(CACHE, exist_ok=True)
    with open(LOG, "a") as f:
        f.write(line + "\n")


def probe(timeout_s: float = 180.0) -> bool:
    """Healthy = devices init AND a LIVE fresh-shape compile both finish
    (snippet shared with bench.probe_accelerator — one probe semantic)."""
    sys.path.insert(0, REPO)
    from bench import probe_snippet

    code, env = probe_snippet()
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True,
                             timeout=timeout_s, env=env)
        if out.returncode == 0 and '"platform"' in out.stdout:
            info = json.loads(out.stdout.strip().splitlines()[-1])
            log(f"probe OK: platform={info['platform']}")
            return info["platform"] != "cpu"
        log(f"probe rc={out.returncode}: {out.stderr.strip()[-200:]}")
    except subprocess.TimeoutExpired:
        log(f"probe timed out after {timeout_s:.0f}s")
    except Exception as e:                               # noqa: BLE001
        log(f"probe error: {e!r}")
    return False


def run_stage(name: str, cmd, timeout_s: float, env=None) -> bool:
    log(f"stage {name}: {' '.join(cmd)} (deadline {timeout_s:.0f}s)")
    t0 = time.time()
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=timeout_s, cwd=REPO,
                             env=dict(os.environ, **(env or {})))
        tail = (out.stdout.strip() or out.stderr.strip())[-2000:]
        log(f"stage {name} rc={out.returncode} in {time.time()-t0:.0f}s:\n"
            f"{tail}")
        if name == "bench":
            # bench.py ALWAYS exits 0 with a JSON line (the driver contract)
            # — a tunnel death mid-run yields rc=0 with an "error" field.
            # Success for the pipeline = a clean line with a real value, so
            # a failed bench re-runs on the next healthy probe instead of
            # being marked done with a zero-QPS artifact.
            if out.returncode != 0:
                return False
            for line in reversed(out.stdout.strip().splitlines()):
                if line.startswith("{"):
                    try:
                        obj = json.loads(line)
                    except ValueError:
                        return False
                    # bench failure spellings: "error" (in-process),
                    # "child_error" (watchdog emitted a checkpointed
                    # partial), "tpu_child_error" (CPU-fallback line)
                    ok = (not any(obj.get(k) for k in
                                  ("error", "child_error",
                                   "tpu_child_error"))
                          and obj.get("value", 0) > 0
                          and obj.get("platform") != "cpu")
                    if ok:
                        with open(os.path.join(REPO, "reports",
                                               "bench_tpu_live.json"),
                                  "w") as f:
                            f.write(line + "\n")
                    return ok
            return False
        return out.returncode == 0
    except subprocess.TimeoutExpired:
        log(f"stage {name} exceeded {timeout_s:.0f}s — killed")
    except Exception as e:                               # noqa: BLE001
        log(f"stage {name} error: {e!r}")
    return False


def pipeline(stages, done) -> None:
    """Run the not-yet-succeeded stages in order; `done` collects names of
    stages that completed rc=0 so a mid-pipeline tunnel death resumes at
    the failed stage on the next healthy probe instead of exiting."""
    py = sys.executable
    plan = []
    if "1" in stages:
        # BENCH_COLD_BUILD: the recovery run is where the true cold on-chip
        # build_s gets recorded (verdict item 6); the driver's end-of-round
        # bench then loads the warm cache and stays well inside its budget
        plan.append(("bench", [py, "bench.py"], 5600,
                     {"BENCH_BUDGET_S": "5400", "BENCH_COLD_BUILD": "1"}))
    if "2" in stages:
        plan.append(("baseline_configs",
                     [py, "tools/baseline_configs.py",
                      "--configs", "1,2,4"], 7200, None))
    if "3" in stages:
        plan.append(("sweep", [py, "tools/sweep_modes.py", "200000"],
                     3600, None))
        # second index at refine budget 2048: beam recall with a
        # production-quality graph (the 512-budget default caps it)
        plan.append(("sweep_refine2048",
                     [py, "tools/sweep_modes.py", "200000"], 5400,
                     {"SWEEP_REFINE_BUDGET": "2048"}))
    if "6" in stages:
        # verdict item 4 follow-up: where does recall pay for width?
        plan.append(("beam_width", [py, "tools/beam_width_tune.py",
                                    "200000"], 3600, None))
    if "7" in stages:
        # round-5 item 2: strong-graph beam headline on chip — loads the
        # CPU-pre-built index when present (else builds on chip, far
        # faster than the CPU pre-build), then measures beam QPS/recall
        # at MaxCheck 2048/8192 on the real chip
        plan.append(("strong_beam",
                     [py, "tools/strong_beam_build.py", "200000"], 5400,
                     {"STRONG_BEAM_PLATFORM": "tpu"}))
    if "4" in stages:
        plan.append(("dense_tune", [py, "tools/dense_tune.py", "200000"],
                     3600, None))
    if "5" in stages:
        plan.append(("scale_rows", [py, "tools/deep1b_single_chip.py"],
                     7200, None))
    for name, cmd, deadline, env in plan:
        if name in done:
            continue
        if run_stage(name, cmd, deadline, env=env):
            done.add(name)
        elif not probe(60.0):
            # the backend died mid-pipeline — back to probing; this stage
            # and everything after it re-run on the next recovery
            log(f"backend unhealthy after stage {name}; pausing pipeline")
            return


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=540.0)
    ap.add_argument("--once", action="store_true",
                    help="single probe + pipeline attempt, no loop")
    ap.add_argument("--stages", default="1,2,3")
    args = ap.parse_args()
    stages = args.stages.split(",")
    done = set()
    want = {"1": "bench", "2": "baseline_configs", "4": "dense_tune",
            "5": "scale_rows", "6": "beam_width", "7": "strong_beam"}
    total = len([s for s in stages if s in want]) + \
        (2 if "3" in stages else 0)
    while True:
        if probe():
            pipeline(stages, done)
            if len(done) >= total:
                log(f"pipeline complete ({sorted(done)}); exiting")
                return
            log(f"stages done so far: {sorted(done)}")
        if args.once:
            return
        time.sleep(args.interval)


if __name__ == "__main__":
    main()
