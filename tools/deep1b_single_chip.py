"""BASELINE config 3 (Deep1B-10M shape) on ONE chip via dense-only build.

The config's reference topology is 8 servers behind an Aggregator
(/root/reference/AnnService/src/Aggregator/AggregatorService.cpp:206-279);
the TPU framework's mesh equivalent is validated on the virtual 8-device
CPU mesh (tests/test_sharded_bkt.py, reports/MESH_SCALING.md).  What no
round has shown yet is the SCALE on real silicon.  This run puts the full
10M x d96 f32 corpus on a single v5e chip (3.84 GB of vectors in HBM —
the 8-shard system's aggregate, one chip's budget) using BuildGraph=0:
the k-means forest + partition layout build in minutes, and the MXU
partition scan serves the whole corpus with no graph in memory.

A second, smaller config measures the LAION-shape slice (config 5 is
400M x d768 over 16 shards = 25M rows/shard — beyond one chip's HBM at
f32; the measured 1M x d768 slice gives the per-chip d=768 cost model).

Usage: python tools/deep1b_single_chip.py [--configs deep1b,laion]
Appends to reports/BASELINE_CONFIGS.md and prints one JSON line each.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
CACHE = os.path.join(REPO, ".bench_cache")


def _truth_cached(tag, data, queries, k=10, metric="l2"):
    path = os.path.join(CACHE, f"truth_{tag}.npy")
    if os.path.exists(path):
        return np.load(path)
    t = np.zeros((len(queries), k), np.int64)
    if metric == "l2":
        dn = (data.astype(np.float32) ** 2).sum(1)
    step = 64
    for i in range(0, len(queries), step):
        q = queries[i:i + step].astype(np.float32)
        if metric == "l2":
            d = dn[None, :] - 2.0 * (q @ data.T)
        else:
            d = -(q @ data.T)
        idx = np.argpartition(d, k, axis=1)[:, :k]
        row = np.take_along_axis(d, idx, axis=1)
        order = np.argsort(row, axis=1)
        t[i:i + step] = np.take_along_axis(idx, order, axis=1)
    os.makedirs(CACHE, exist_ok=True)
    np.save(path, t)
    return t


def _measure(index, queries, truth, k, mcs, out, prefix):
    import bench

    for mc in mcs:
        index.set_parameter("MaxCheck", str(mc))
        index.search_batch(queries[:1024], k)
        index.search_batch(queries, k)
        t0 = time.perf_counter()
        reps = 2
        ids = None
        for _ in range(reps):
            _, got = index.search_batch(queries, k)
            ids = got if ids is None else ids
        qps = reps * len(queries) / (time.perf_counter() - t0)
        lat = []
        for _ in range(5):
            tb = time.perf_counter()
            index.search_batch(queries[:1024], k)
            lat.append(time.perf_counter() - tb)
        out[f"{prefix}mc{mc}"] = {
            "qps": round(qps, 1),
            "recall_at_10": round(bench.recall_at_k(ids, truth, k), 4),
            "p50_batch1024_ms": round(
                float(np.percentile(lat, 50)) * 1000, 2)}
        print(json.dumps({prefix + "mc": mc, **out[f"{prefix}mc{mc}"]}),
              flush=True)


def run_deep1b(small=False):
    import jax

    import sptag_tpu as sp

    n, d, nq, k = 10_000_000, 96, 4096, 10
    if small:                     # CPU smoke run of the exact code path
        n, nq = 200_000, 256
    rng = np.random.default_rng(23)
    centers = rng.standard_normal((4096, d)).astype(np.float32) * 3.0
    assign = rng.integers(0, 4096, n)
    data = centers[assign] + rng.standard_normal((n, d)).astype(np.float32)
    queries = (centers[rng.integers(0, 4096, nq)]
               + rng.standard_normal((nq, d)).astype(np.float32))
    del assign

    out = {"config": "Deep1B-10M-shape 10M x d96 f32 L2, dense-only, "
                     "single chip", "platform": jax.devices()[0].platform}
    t0 = time.time()
    truth = _truth_cached("deep1b_10m" if not small else "deep1b_smoke",
                          data, queries, k)
    out["truth_s"] = round(time.time() - t0, 1)

    t0 = time.time()
    idx = sp.create_instance("BKT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    for name, val in [("BuildGraph", "0"), ("BKTNumber", "1"),
                      ("BKTKmeansK", "32"), ("BKTLeafSize", "384"),
                      ("DenseClusterSize", "512"), ("MaxCheck", "8192")]:
        idx.set_parameter(name, val)
    idx.build(data)
    out["build_s"] = round(time.time() - t0, 1)
    print(json.dumps({"built": out["build_s"]}), flush=True)

    _measure(idx, queries, truth, k, [4096, 8192, 16384], out, "")
    return out


def run_laion_slice(small=False):
    import jax

    import sptag_tpu as sp
    from bench import cosine_truth

    n, d, nq, k = 1_000_000, 768, 2048, 10
    if small:
        n, nq = 100_000, 256
    rng = np.random.default_rng(29)
    centers = rng.standard_normal((1024, d)).astype(np.float32)
    data = (centers[rng.integers(0, 1024, n)] * 2.0
            + rng.standard_normal((n, d)).astype(np.float32))
    queries = (centers[rng.integers(0, 1024, nq)] * 2.0
               + rng.standard_normal((nq, d)).astype(np.float32))

    out = {"config": "LAION-shape slice 1M x d768 f32 cosine, dense-only, "
                     "single chip (per-shard cost model for config 5)",
           "platform": jax.devices()[0].platform}
    t0 = time.time()
    tag = "laion_1m_d768" if not small else "laion_smoke"
    path = os.path.join(CACHE, f"truth_{tag}.npy")
    if os.path.exists(path):
        truth = np.load(path)
    else:
        truth = cosine_truth(data, queries, k)
        os.makedirs(CACHE, exist_ok=True)
        np.save(path, truth)
    out["truth_s"] = round(time.time() - t0, 1)

    t0 = time.time()
    idx = sp.create_instance("BKT", "Float")
    idx.set_parameter("DistCalcMethod", "Cosine")
    for name, val in [("BuildGraph", "0"), ("BKTNumber", "1"),
                      ("BKTKmeansK", "32"), ("BKTLeafSize", "384"),
                      ("DenseClusterSize", "512"), ("MaxCheck", "8192")]:
        idx.set_parameter(name, val)
    idx.build(data)
    out["build_s"] = round(time.time() - t0, 1)
    print(json.dumps({"built": out["build_s"]}), flush=True)

    _measure(idx, queries, truth, k, [4096, 8192], out, "")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="deep1b,laion")
    ap.add_argument("--small", action="store_true",
                    help="CPU smoke run of the exact code paths")
    args = ap.parse_args()
    if args.small:
        import jax

        jax.config.update("jax_platforms", "cpu")
    # resumable builds: the 10M-row tree stage is the long pole here — a
    # tunnel death mid-build resumes instead of restarting (build_ckpt.py)
    os.environ.setdefault("SPTAG_TPU_BUILD_CKPT",
                          os.path.join(CACHE, "build_ckpt"))
    results = []
    for name in args.configs.split(","):
        fn = {"deep1b": run_deep1b, "laion": run_laion_slice}[name]
        try:
            r = fn(small=args.small)
        except Exception as e:                           # noqa: BLE001
            r = {"config": name, "error": repr(e)[:300]}
        results.append(r)
        print(json.dumps(r), flush=True)

    with open(os.path.join(REPO, "reports", "BASELINE_CONFIGS.md"),
              "a") as f:
        f.write(f"\n## Single-chip scale rows ({time.strftime('%Y-%m-%d')},"
                " dense-only build%s)\n\n"
                % (" — SMOKE SHAPES, not the real config" if args.small
                   else ""))
        for r in results:
            if "error" in r:
                f.write(f"* {r['config']}: ERROR {r['error']}\n")
                continue
            f.write(f"* **{r['config']}** ({r['platform']}): build "
                    f"{r['build_s']}s; ")
            f.write("; ".join(
                f"MaxCheck {key.lstrip('mc')}: {v['qps']} QPS @ "
                f"recall {v['recall_at_10']} (p50 {v['p50_batch1024_ms']}ms"
                f"/1024q)"
                for key, v in r.items() if key.startswith("mc")) + "\n")


if __name__ == "__main__":
    main()
