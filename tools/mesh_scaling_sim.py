"""Simulated mesh scaling evidence (verdict round-2 weak #6): per-shard QPS
on virtual CPU meshes + all-gather merge cost accounting.

MESH_SIM_LADDER (default "1,2,4,8") sets the device-count ladder; the
virtual device count is its maximum — "16" simulates BASELINE config 5's
16-shard LAION topology on one host."""
import os, sys, time, json
LADDER = tuple(int(x) for x in
               os.environ.get("MESH_SIM_LADDER", "1,2,4,8").split(","))
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS","") +
                           f" --xla_force_host_platform_device_count={max(LADDER)}")
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
from sptag_tpu.core.types import DistCalcMethod
from sptag_tpu.parallel.sharded import ShardedBKTIndex, make_mesh

rng = np.random.default_rng(12)
n, d, nq = 64_000, 64, 256
centers = rng.standard_normal((64, d)).astype(np.float32) * 3
data = centers[rng.integers(0, 64, n)] + rng.standard_normal((n, d)).astype(np.float32)
queries = centers[rng.integers(0, 64, nq)] + rng.standard_normal((nq, d)).astype(np.float32)
dn = (data**2).sum(1)
truth = np.argsort(dn[None,:] - 2*(queries @ data.T), axis=1)[:, :10]
P = {"BKTNumber":1,"BKTKmeansK":8,"TPTNumber":2,"TPTLeafSize":500,
     "NeighborhoodSize":16,"CEF":64,"MaxCheckForRefineGraph":256,
     "RefineIterations":1,"MaxCheck":2048}

devs = jax.devices()
out = []
for nd in LADDER:
    mesh = make_mesh(devs[:nd])
    idx = ShardedBKTIndex.build(data, DistCalcMethod.L2, mesh=mesh, params=P, dense=True)
    for mode, fn in (("beam", lambda q: idx.search(q, 10)),
                     ("dense", lambda q: idx.search_dense(q, 10, max_check=2048))):
        fn(queries)  # compile+warm
        t0 = time.perf_counter(); fn(queries); dt = time.perf_counter() - t0
        _, ids = fn(queries)
        rec = float(np.mean([len(set(np.asarray(ids)[i,:10]) & set(truth[i]))/10 for i in range(nq)]))
        out.append({"devices": nd, "mode": mode, "qps": round(nq/dt,1), "recall": round(rec,4)})
        print(json.dumps(out[-1]), flush=True)
