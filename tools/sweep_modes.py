"""MaxCheck sweep: beam vs dense recall/latency curves (VERDICT item 5).

Mirrors the reference IndexSearcher harness loop
(/root/reference/AnnService/src/IndexSearcher/main.cpp:131-190): one index,
a list of MaxCheck values, per-value recall@10 + latency percentiles — run
for BOTH search modes so the TPU-only dense mode's curve can be compared
against the reference-semantics beam walk's.

Writes a markdown table to reports/MAXCHECK_SWEEP.md and prints it.

Usage: python tools/sweep_modes.py [n] [out_path]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    out_path = sys.argv[2] if len(sys.argv) > 2 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "reports", "MAXCHECK_SWEEP.md")
    platform = os.environ.get("BENCH_PLATFORM")
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    from sptag_tpu.utils import enable_compile_cache

    enable_compile_cache()

    import sptag_tpu as sp
    from bench import (make_dataset, _bkt_params, l2_truth, build_or_load,
                       build_headline_f32,
                       recall_at_k)

    k = 10
    batch = 256
    # one generation serves both harnesses: the latency sweep uses the
    # first 512 queries, the throughput section the full 2048
    data, queries_t = make_dataset(n=n, nq=2048)
    queries = queries_t[:512]
    truth_t = l2_truth(data, queries_t, k)
    truth = truth_t[:512]

    # SWEEP_REFINE_BUDGET overrides MaxCheckForRefineGraph at build time
    # (own cache tag).  The bench's default 512 targets the <600 s cold
    # build; beam recall is capped by it (reports/MAXCHECK_SWEEP.md: 512
    # capped 100k beam at 0.855, 2048 reached 0.992) — a 2048-budget
    # index shows the walk's recall with a production-quality graph.
    refine = int(os.environ.get("SWEEP_REFINE_BUDGET", "0"))

    def build():
        # refine==0 writes the SHARED bkt_f32_n{n} tag — must be the
        # bench's own builder so the cache cannot drift (bench.py comment
        # above build_headline_f32); the refine override builds under its
        # own suffixed tag and layers the one extra param on top
        if not refine:
            return build_headline_f32(n, data)
        index = sp.create_instance("BKT", "Float")
        index.set_parameter("DistCalcMethod", "L2")
        _bkt_params(index, n)
        index.set_parameter("MaxCheckForRefineGraph", str(refine))
        index.build(data)
        return index

    tag = f"bkt_f32_n{n}" + (f"_refine{refine}" if refine else "")
    index, build_s, cached = build_or_load(tag, build, 1e9)
    dev = jax.devices()[0].platform

    lines = [
        (f"## Refine budget {refine} (graph quality run)" if refine
         else "# MaxCheck sweep — beam vs dense recall/latency"),
        "",
        f"Corpus: synthetic clustered SIFT-like, n={n}, d=128, L2; "
        f"{len(queries)} queries, recall@{k} vs exact ground truth; "
        f"platform={dev}; build_s={build_s:.1f} (cached={cached}).",
        "",
        "Harness parity: reference IndexSearcher MaxCheck sweep "
        "(src/IndexSearcher/main.cpp:131-190).",
        "",
        "| MaxCheck | mode | recall@10 | avg ms/query | p95 batch ms | "
        "p99 batch ms |",
        "|---|---|---|---|---|---|",
    ]
    for max_check in (512, 1024, 2048, 4096, 8192):
        index.set_parameter("MaxCheck", str(max_check))
        # "auto" (VERDICT r3 item 4): per-request crossover — the row must
        # never be worse than the WORSE of beam/dense at the same budget
        for mode in ("beam", "dense", "auto"):
            index.set_parameter("SearchMode", mode)
            index.search_batch(queries[:batch], k)      # compile/warm
            times = []
            ids_all = np.zeros((len(queries), k), np.int64)
            for i in range(0, len(queries), batch):
                t0 = time.perf_counter()
                _, ids = index.search_batch(queries[i:i + batch], k)
                times.append(time.perf_counter() - t0)
                ids_all[i:i + batch] = ids[:, :k]
            recall = recall_at_k(ids_all, truth, k)
            total = sum(times)
            lines.append(
                f"| {max_check} | {mode} | {recall:.4f} | "
                f"{total / len(queries) * 1000:.2f} | "
                f"{np.percentile(times, 95) * 1000:.1f} | "
                f"{np.percentile(times, 99) * 1000:.1f} |")
            print(lines[-1], flush=True)

    # Throughput at MaxCheck 2048 (VERDICT item 4's "beam >= 2,000 QPS at
    # recall >= 0.95" is a THROUGHPUT target): one large chunked batch —
    # `lax.map` folds the chunk loop into a single device program, so the
    # tunneled backend's ~60 ms round trip is paid twice per call instead
    # of once per 256-query batch.  The small-batch loop above remains the
    # latency harness (reference IndexSearcher reports per-query latency).
    nq_t = len(queries_t)
    index.set_parameter("MaxCheck", "2048")
    lines += ["", "### Throughput (2048-query chunked batch, MaxCheck=2048)",
              "", "| mode | recall@10 | QPS |", "|---|---|---|"]
    for mode in ("beam", "dense"):
        index.set_parameter("SearchMode", mode)
        index.search_batch(queries_t, k)            # compile + warm
        best = float("inf")
        ids = None
        for _ in range(3):
            t0 = time.perf_counter()
            _, ids = index.search_batch(queries_t, k)
            best = min(best, time.perf_counter() - t0)
        recall = recall_at_k(ids[:, :k], truth_t, k)
        lines.append(f"| {mode} | {recall:.4f} | {nq_t / best:,.0f} |")
        print(lines[-1], flush=True)

    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "a" if refine else "w") as f:
        f.write(("\n" if refine else "") + "\n".join(lines) + "\n")
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
