"""BASELINE.json config-by-config measurement (round-3 verdict item 5).

Runs the reference's headline benchmark shapes at EXACT dim/dtype/metric —
synthesized corpora (the image has zero network egress, so SIFT1M/GloVe/
Deep1B/MS-MARCO/LAION themselves are unfetchable; BASELINE.md records
this substitution) against the reference harness semantics
(/root/reference/AnnService/src/IndexSearcher/main.cpp:66-228: recall@10,
latency percentiles over batch wall time).

Configs (BASELINE.json `configs`):
  1. SIFT1M-shape   : 1,000,000 x d128 float32 L2, BKT
  2. GloVe-100-shape:   400,000 x d100 float32 cosine, KDT
  4. MS-MARCO-shape :   200,000 x d384 int8 cosine, BKT
(3/5 — Deep1B-10M 8-shard and LAION 16-shard — need multi-chip hardware;
their sharded program is validated on the virtual mesh by
tests/test_sharded_bkt.py and __graft_entry__.dryrun_multichip.)

Builds are disk-cached under .bench_cache/ (a 1M-row build costs ~45 min
of CPU); the measurement pass runs on whatever backend is live, so the
intended flow is: build once on CPU, measure on the chip.

Usage:
  python tools/baseline_configs.py [--build-only] [--configs 1,2,4]
Emits one JSON line per config and appends a table row to
reports/BASELINE_CONFIGS.md.
"""

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
CACHE = os.path.join(REPO, ".bench_cache")

from bench import (  # noqa: E402
    build_or_load,
    exact_topk,
    make_dataset,
    probe_accelerator,
)


def _truth_cached(tag, fn):
    path = os.path.join(CACHE, f"truth_{tag}.npy")
    if os.path.exists(path):
        return np.load(path)
    t = fn()
    os.makedirs(CACHE, exist_ok=True)
    np.save(path, t)
    return t


def _recall(ids, truth, k=10):
    return float(np.mean([len(set(ids[i, :k]) & set(truth[i])) / k
                          for i in range(len(truth))]))


def _measure(index, queries, k, batch=1024, repeats=2):
    index.search_batch(queries[:batch], k)          # compile
    index.search_batch(queries, k)                  # warm full shape
    t0 = time.perf_counter()
    done = 0
    ids = None
    for r in range(repeats):
        _, out = index.search_batch(queries, k)
        if ids is None:
            ids = out
        done += len(queries)
    qps = done / (time.perf_counter() - t0)
    lat = []
    for _ in range(10):
        tb = time.perf_counter()
        index.search_batch(queries[:batch], k)
        lat.append(time.perf_counter() - tb)
    return ids, qps, float(np.percentile(lat, 50)) * 1000


def config_sift1m(build_only):
    """Config 1: SIFT1M shape — 1M x d128 f32 L2 BKT."""
    import sptag_tpu as sp

    n, d, nq, k = 1_000_000, 128, 2048, 10
    data, queries = make_dataset(n=n, d=d, nq=nq, seed=17)
    def _build():
        idx = sp.create_instance("BKT", "Float")
        idx.set_parameter("DistCalcMethod", "L2")
        for name, value in [("BKTNumber", "1"), ("BKTKmeansK", "32"),
                            ("TPTNumber", "8"), ("TPTLeafSize", "1500"),
                            ("NeighborhoodSize", "32"), ("CEF", "256"),
                            ("MaxCheckForRefineGraph", "1024"),
                            ("RefineIterations", "2"), ("MaxCheck", "4096"),
                            ("DenseClusterSize", "512")]:
            idx.set_parameter(name, value)
        idx.build(data)
        return idx

    # bench.build_or_load: one cache policy (cache-version suffix +
    # BENCH_COLD_BUILD) shared with the headline bench
    idx, build_s, cached = build_or_load("baseline_sift1m_shape", _build,
                                         budget_s=1e9)
    if build_only:
        return {"config": "SIFT1M-shape", "build_s": round(build_s, 1),
                "build_cached": cached}
    # budget scales with corpus size (the reference's own default is 8192):
    # at 1M rows MaxCheck 4096 probes 8/2000 blocks and caps recall at
    # 0.843; 8192 reaches 0.976 (measured CPU sweep, round 3)
    idx.set_parameter("MaxCheck", "8192")
    truth = _truth_cached("sift1m_shape",
                          lambda: _chunked_truth(data, queries, k))
    ids, qps, p50 = _measure(idx, queries, k)
    return {"config": "SIFT1M-shape 1M x d128 f32 L2 BKT",
            "qps": round(qps, 1), "recall_at_10": _recall(ids, truth),
            "p50_batch_ms": round(p50, 2), "build_s": round(build_s, 1),
            "build_cached": cached, "n": n}


def _chunked_truth(data, queries, k):
    dn = (data ** 2).sum(1)
    out = np.zeros((len(queries), k), np.int64)
    for i in range(0, len(queries), 128):
        out[i:i + 128] = exact_topk(data, dn, queries[i:i + 128], k)
    return out


def config_glove100(build_only):
    """Config 2: GloVe-100 shape — 400k x d100 f32 cosine KDT."""
    import sptag_tpu as sp
    from bench import cosine_truth

    n, d, nq, k = 400_000, 100, 2048, 10
    data, queries = make_dataset(n=n, d=d, nq=nq, seed=18)
    def _build():
        idx = sp.create_instance("KDT", "Float")
        idx.set_parameter("DistCalcMethod", "Cosine")
        for name, value in [("KDTNumber", "2"), ("TPTNumber", "8"),
                            ("TPTLeafSize", "1200"),
                            ("NeighborhoodSize", "32"), ("CEF", "256"),
                            ("MaxCheckForRefineGraph", "1024"),
                            ("RefineIterations", "2"), ("MaxCheck", "4096"),
                            ("DenseClusterSize", "512")]:
            idx.set_parameter(name, value)
        idx.build(data)
        return idx

    idx, build_s, cached = build_or_load("baseline_glove100_shape", _build,
                                         budget_s=1e9)
    if build_only:
        return {"config": "GloVe-100-shape", "build_s": round(build_s, 1),
                "build_cached": cached}
    truth = _truth_cached("glove100_shape",
                          lambda: cosine_truth(data, queries, k))
    ids, qps, p50 = _measure(idx, queries, k)
    out = {"config": "GloVe-100-shape 400k x d100 f32 cosine KDT",
           "qps": round(qps, 1), "recall_at_10": _recall(ids, truth),
           "p50_batch_ms": round(p50, 2), "build_s": round(build_s, 1),
           "build_cached": cached, "n": n}
    try:
        # TPU fast path on the same index: kd-cell MXU scan + closure
        # replicas (kd cells lose boundary neighbors; measured 0.859 ->
        # 0.975 at replicas=2, reports/KDT_DENSE_REPLICAS.md)
        idx.set_parameter("SearchMode", "dense")
        idx.set_parameter("DenseReplicas", "2")
        idx._dense = None                    # rebuild snapshot w/ replicas
        ids_d, qps_d, p50_d = _measure(idx, queries, k)
        out.update({"dense_qps": round(qps_d, 1),
                    "dense_recall_at_10": _recall(ids_d, truth),
                    "dense_p50_batch_ms": round(p50_d, 2)})
    except Exception as e:                               # noqa: BLE001
        out["dense_error"] = repr(e)[:200]
    return out


def config_msmarco(build_only):
    """Config 4: MS-MARCO shape — 200k x d384 int8 cosine BKT."""
    import sptag_tpu as sp
    from bench import cosine_truth

    n, d, nq, k = 200_000, 384, 2048, 10
    data, queries = make_dataset(n=n, d=d, nq=nq, seed=19, dtype=np.int8)
    def _build():
        idx = sp.create_instance("BKT", "Int8")
        idx.set_parameter("DistCalcMethod", "Cosine")
        for name, value in [("BKTNumber", "1"), ("BKTKmeansK", "32"),
                            ("TPTNumber", "8"), ("TPTLeafSize", "1000"),
                            ("NeighborhoodSize", "32"), ("CEF", "256"),
                            ("MaxCheckForRefineGraph", "512"),
                            ("RefineIterations", "2"), ("MaxCheck", "4096"),
                            ("DenseClusterSize", "512")]:
            idx.set_parameter(name, value)
        idx.build(data)
        return idx

    idx, build_s, cached = build_or_load("baseline_msmarco_shape", _build,
                                         budget_s=1e9)
    if build_only:
        return {"config": "MS-MARCO-shape", "build_s": round(build_s, 1),
                "build_cached": cached}
    idx.set_parameter("DenseQueryGroup", "32")
    idx.set_parameter("DenseUnionFactor", "4")
    truth = _truth_cached("msmarco_shape",
                          lambda: cosine_truth(data, queries, k))
    ids, qps, p50 = _measure(idx, queries, k)
    return {"config": "MS-MARCO-shape 200k x d384 int8 cosine BKT",
            "qps": round(qps, 1), "recall_at_10": _recall(ids, truth),
            "p50_batch_ms": round(p50, 2), "build_s": round(build_s, 1),
            "build_cached": cached, "n": n}


CONFIGS = {"1": config_sift1m, "2": config_glove100, "4": config_msmarco}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--build-only", action="store_true")
    ap.add_argument("--configs", default="1,2,4")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (skip the TPU probe)")
    args = ap.parse_args()

    if args.cpu:
        import jax
        jax.config.update("jax_platforms", "cpu")
        platform = "cpu"
    else:
        platform, err, _, _cached = probe_accelerator(budget_s=600)
        if platform is None:
            import jax
            jax.config.update("jax_platforms", "cpu")
            platform = "cpu"

    results = []
    for key in args.configs.split(","):
        key = key.strip()
        if key not in CONFIGS:
            continue
        try:
            r = CONFIGS[key](args.build_only)
        except Exception as e:                       # noqa: BLE001
            r = {"config": key, "error": repr(e)[:300]}
        r["platform"] = platform
        print(json.dumps(r), flush=True)
        results.append(r)

    if not args.build_only and results:
        path = os.path.join(REPO, "reports", "BASELINE_CONFIGS.md")
        new = not os.path.exists(path)
        with open(path, "a") as f:
            if new:
                f.write("# BASELINE configs at real shapes\n\n"
                        "Synthesized at exact shape/dtype/metric (no "
                        "egress for the real sets — bench.py docstring); "
                        "harness semantics per IndexSearcher/main.cpp:"
                        "66-228.\n\n"
                        "| config | platform | QPS | recall@10 | p50 ms | "
                        "build_s (cached) |\n|---|---|---|---|---|---|\n")
            for r in results:
                if "error" in r:
                    f.write(f"| {r['config']} | {r['platform']} | error: "
                            f"{r['error'][:80]} | | | |\n")
                else:
                    f.write(
                        f"| {r['config']} | {r['platform']} | {r['qps']} | "
                        f"{r['recall_at_10']:.4f} | {r['p50_batch_ms']} | "
                        f"{r['build_s']} ({r['build_cached']}) |\n")


if __name__ == "__main__":
    main()
