"""10M-row GRAPH-mode sharded build proof (VERDICT r3 item 9).

Config 3's earlier 10M evidence was dense-only (BuildGraph=0); this drives
the resumable sharded *graph* build path (BuildGraph=1) at 10M x d96 on
the 8-device virtual CPU mesh with stage checkpoints, then smoke-checks
beam recall on a query sample and appends a SCALE.md row.

Resumability is part of the proof: run with --kill-after S to SIGKILL the
build mid-flight; re-running serves every FINISHED shard's stages from
its retained checkpoint (the sharded build keeps per-shard checkpoints
until all shards succeed — parallel/sharded.py) and resumes the
interrupted shard at its first incomplete stage.  The driver for that
two-phase drive:

    python tools/scale_10m_graph.py --n 10000000 --kill-after 600
    python tools/scale_10m_graph.py --n 10000000        # resumes

Build knobs keep wall time bounded on CPU: dense-mode grouped refine for
EVERY pass (FinalRefineSearchMode=same — the walk-quality guardrail is a
reference-consumer concern, orthogonal to proving the build path at
scale), RefineIterations=1, small TPT fanout.
"""

import argparse
import json
import logging
import os
import signal
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def corpus(n, d, seed=5):
    import numpy as np

    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((1024, d)).astype(np.float32) * 3.0
    out = np.empty((n, d), np.float32)
    step = 1_000_000
    for i in range(0, n, step):
        m = min(step, n - i)
        assign = rng.integers(0, 1024, m)
        out[i:i + m] = (centers[assign]
                        + rng.standard_normal((m, d)).astype(np.float32))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--n", type=int, default=10_000_000)
    ap.add_argument("--d", type=int, default=96)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--kill-after", type=float, default=0,
                    help="SIGKILL this process after S seconds (resume "
                         "drive phase 1)")
    ap.add_argument("--ckpt", default=os.path.join(REPO, ".bench_cache",
                                                   "scale10m_ckpt"))
    args = ap.parse_args()

    # INFO: the per-pass sampled graph-accuracy lines (graph/rng.py
    # "RNG refine pass i/n width=w acc=a") are the build-quality log the
    # refined run exists to produce — without this they are dropped
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(message)s")

    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count="
                    f"{args.devices}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["SPTAG_TPU_BUILD_CKPT"] = args.ckpt
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from sptag_tpu.core.types import DistCalcMethod
    from sptag_tpu.parallel.sharded import ShardedBKTIndex, make_mesh

    if args.kill_after > 0:
        # watchdog THREAD, not a SIGALRM handler: Python signal handlers
        # only run between bytecodes on the main thread, and a single
        # jitted refine call can sit in native XLA for many minutes — a
        # deferred kill would silently degenerate the two-phase resume
        # drive into one complete build.  A thread delivers SIGKILL (no
        # cleanup, exactly what the drive wants) on time regardless.
        import threading

        pid = os.getpid()

        def _kill():
            print(f"[scale10m] SIGKILL after {args.kill_after}s "
                  "(resume drive)", flush=True)
            os.kill(pid, signal.SIGKILL)
        t = threading.Timer(args.kill_after, _kill)
        t.daemon = True
        t.start()

    t0 = time.time()
    data = corpus(args.n, args.d)
    t_data = time.time() - t0
    print(f"[scale10m] corpus {args.n}x{args.d} in {t_data:.0f}s",
          flush=True)

    params = {
        "BKTNumber": 1, "BKTKmeansK": 32,
        # round-5 measured: at 10M the refined run with speed knobs
        # (TPT 4, CEF 64, refine budget 256) came out WORSE than
        # candidates-only (0.469 vs 0.589 @2048) — the starved refine
        # (nprobe=1 per search) replaces TPT candidate edges with
        # near-random results.  Candidate-graph quality (TPT count, CEF)
        # is the honest lever at this scale; both overridable.
        "TPTNumber": int(os.environ.get("SCALE10M_TPT", "4")),
        "TPTLeafSize": 1000, "NeighborhoodSize": 32,
        "CEF": int(os.environ.get("SCALE10M_CEF", "64")),
        # SCALE10M_REFINE=0 selects the candidates-only graph (TPT
        # all-pairs + RNG prune + connectivity repair, no re-search
        # passes) — the wall-time-bounded configuration for the 10M CPU
        # proof; 1 (default) adds one grouped dense refine pass (the
        # 500k kill/resume drive's quality point)
        "MaxCheckForRefineGraph": 256,
        "RefineIterations": int(os.environ.get("SCALE10M_REFINE", "1")),
        "MaxCheck": 2048, "RefineQueryGroup": 32,
        "RefineSearchMode": "dense", "FinalRefineSearchMode": "same",
        "BuildGraph": 1,
    }
    t1 = time.time()
    # SCALE10M_DENSE=1 (default) packs the per-shard MXU tree-partition
    # layout too, so the quality ladder below can measure BOTH modes:
    # beam (the reference-parity walk) and dense (the TPU flagship —
    # measured at 250k it responds to budget all the way up where the
    # walk's recall is seed-coverage-bound; reports/SCALE.md round-5).
    # RSS caveat: the dense pack allocates a padded second corpus copy
    # AFTER the build's resume checkpoints retire (~4 GB host-side at
    # 10M x d96) — on a memory-tight box set SCALE10M_DENSE=0 or a
    # mid-pack OOM costs the whole unresumable build.
    want_dense = os.environ.get("SCALE10M_DENSE", "1") == "1"
    index = ShardedBKTIndex.build(data, DistCalcMethod.L2,
                                  mesh=make_mesh(), params=params,
                                  dense=want_dense)
    build_s = time.time() - t1
    print(f"[scale10m] sharded graph build {build_s:.0f}s", flush=True)

    # beam recall smoke on a sample vs exact truth over the full corpus
    rng = np.random.default_rng(99)
    qidx = rng.integers(0, args.n, 64)
    queries = data[qidx] + 0.05 * rng.standard_normal(
        (64, args.d)).astype(np.float32)
    t2 = time.time()
    _, ids = index.search(queries, 10)
    search_s = time.time() - t2
    # budget ladder: recall at fixed MaxCheck decays with corpus size
    # (2048 candidates is ~0.02% coverage at 10M) — measure the graph's
    # quality envelope, not one rung (VERDICT r4 item 4)
    ladder_ids = {}
    for mc in (8192, 16384, 32768):
        tl = time.time()
        _, ids_mc = index.search(queries, 10, max_check=mc)
        ladder_ids[mc] = (ids_mc, round(time.time() - tl, 2))
    dense_ladder_ids = {}
    if want_dense:
        for mc in (8192, 16384, 32768):
            tl = time.time()
            _, ids_mc = index.search_dense(queries, 10, max_check=mc)
            dense_ladder_ids[mc] = (ids_mc, round(time.time() - tl, 2))
    # exact truth in 1M-row blocks
    best_d = np.full((64, 10), np.inf, np.float64)
    best_i = np.full((64, 10), -1, np.int64)
    qn = (queries.astype(np.float64) ** 2).sum(1)[:, None]
    for i in range(0, args.n, 1_000_000):
        blk = data[i:i + 1_000_000].astype(np.float64)
        dmat = qn + (blk ** 2).sum(1)[None, :] - 2.0 * (
            queries.astype(np.float64) @ blk.T)
        cat_d = np.concatenate([best_d, dmat], axis=1)
        cat_i = np.concatenate(
            [best_i, np.arange(i, i + blk.shape[0])[None, :].repeat(
                64, axis=0)], axis=1)
        sel = np.argpartition(cat_d, 10, axis=1)[:, :10]
        best_d = np.take_along_axis(cat_d, sel, axis=1)
        best_i = np.take_along_axis(cat_i, sel, axis=1)
    def _recall(got):
        return float(np.mean([
            len(set(int(v) for v in got[q] if v >= 0)
                & set(int(v) for v in best_i[q])) / 10 for q in range(64)]))

    recall = _recall(ids)
    ladder = {str(mc): {"recall_at_10": round(_recall(v[0]), 4),
                        "search64_s": v[1]}
              for mc, v in ladder_ids.items()}
    dense_ladder = {str(mc): {"recall_at_10": round(_recall(v[0]), 4),
                              "search64_s": v[1]}
                    for mc, v in dense_ladder_ids.items()}
    result = {
        "n": args.n, "d": args.d, "devices": args.devices,
        "build_s": round(build_s, 1), "corpus_s": round(t_data, 1),
        "search64_s": round(search_s, 2), "recall_at_10": round(recall, 4),
        "ladder": ladder, "dense_ladder": dense_ladder,
        # the build's OWN signal (any shard resumed from checkpoints) —
        # a non-empty checkpoint dir alone can be stale foreign state
        "resumed": bool(getattr(index, "build_resumed", False)),
        "params": params,
    }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
