"""One-off live-TPU experiment: grouped-probing compile health + sweep.

Run from /root/repo: `python tools/_tpu_group_experiment.py`
Prints one JSON line per probe; safe to re-run (cached index).
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp

    import bench
    import sptag_tpu as sp
    from sptag_tpu.ops import pallas_kernels
    from sptag_tpu.utils import enable_compile_cache

    enable_compile_cache()
    out = {"platform": jax.devices()[0].platform}

    # A) compile-service health: a never-before-seen tiny XLA program
    t0 = time.time()
    try:
        x = jnp.arange(1237, dtype=jnp.float32)
        y = jax.jit(lambda v: (v * 3.13).sum())(x)
        float(y)
        out["xla_new_compile"] = f"ok {time.time()-t0:.1f}s"
    except Exception as e:                              # noqa: BLE001
        out["xla_new_compile"] = repr(e)[:200]
        print(json.dumps(out))
        return

    # B) grouped Pallas kernel compile, tiny shape
    t0 = time.time()
    try:
        rng = np.random.default_rng(0)
        perm = jnp.asarray(rng.standard_normal((8, 64, 128), np.float32))
        qs = jnp.asarray(rng.standard_normal((32, 128), np.float32))
        un = jnp.asarray(rng.integers(0, 8, (2, 4)).astype(np.int32))
        d = pallas_kernels.group_block_dots(perm, qs, un)
        np.asarray(d)
        out["grouped_pallas_compile"] = f"ok {time.time()-t0:.1f}s"
    except Exception as e:                              # noqa: BLE001
        out["grouped_pallas_compile"] = repr(e)[:300]

    # C) per-query Pallas kernel compile (fresh tiny shape)
    t0 = time.time()
    try:
        topc = jnp.asarray(rng.integers(0, 8, (32, 3)).astype(np.int32))
        d = pallas_kernels.probe_block_dots(
            jnp.asarray(rng.standard_normal((8, 64, 128), np.float32)),
            qs, topc)
        np.asarray(d)
        out["perquery_pallas_compile"] = f"ok {time.time()-t0:.1f}s"
    except Exception as e:                              # noqa: BLE001
        out["perquery_pallas_compile"] = repr(e)[:300]
    print(json.dumps(out))

    # D) sweep on the cached 200k index
    data, queries = bench.make_dataset(n=200_000, nq=4096)
    truth = bench.l2_truth(data, queries, 10)
    index = sp.load_index(".bench_cache/bkt_f32_n200000_v3")

    def run(tag, group, uf):
        index.set_parameter("DenseQueryGroup", str(group))
        index.set_parameter("DenseUnionFactor", str(uf))
        index.search_batch(queries, 10)           # warm/compile
        t0 = time.perf_counter()
        _, ids = index.search_batch(queries, 10)
        dt = time.perf_counter() - t0
        rec = bench.recall_at_k(np.asarray(ids[:, :10], np.int64), truth, 10)
        row = {"cfg": tag, "qps": round(4096 / dt, 1),
               "recall": round(rec, 4),
               "geff": index._get_dense().last_effective_group,
               "pallas_disabled": pallas_kernels._DISABLED,
               "grouped_disabled": pallas_kernels._GROUP_DISABLED}
        print(json.dumps(row))
        sys.stdout.flush()

    run("ungrouped", 0, 2)
    run("G16_U2", 16, 2)
    run("G16_U3", 16, 3)
    run("G16_U4", 16, 4)
    run("G8_U4", 8, 4)


if __name__ == "__main__":
    main()
