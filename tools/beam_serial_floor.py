"""Beam-width budget-efficiency study (VERDICT r4 item 2 analysis).

The reference walk is strictly serial best-first: pop ONE node, expand,
push (BKTIndex.cpp:105-157) — maximal budget efficiency (every scored
candidate was the best known frontier node at its time), minimal wall
speed.  The TPU walk pops B nodes per iteration so the whole batch rides
one compiled loop of T = ceil(MaxCheck/B) steps; wider B cuts the SERIAL
iteration count (the chip's real cost — the loop is overhead-bound, not
bandwidth-bound) but spends budget on pops that serial ordering would
have refined away.

This tool measures that trade on one graph: recall@10 and wall time at
fixed MaxCheck across B in {1, 8, 32, 128} (B=1 approximates the
reference's ordering with batch parallelism over queries).  The gap
between B=1 and wide-B recall at equal MaxCheck IS the width tax; the
wall-time column is why the wide beam exists.

Monkeypatches `engine.beam_width_for` (which deliberately FLOORS the
width at the autoscale) to honor the requested B exactly.

Usage: python tools/beam_serial_floor.py [n] [queries]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import sptag_tpu as sp
    from sptag_tpu.algo import engine as eng

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    nq = int(sys.argv[2]) if len(sys.argv) > 2 else 64
    d = 64
    rng = np.random.default_rng(3)
    centers = rng.standard_normal((256, d)).astype(np.float32) * 4.0
    data = (centers[rng.integers(0, 256, n)]
            + rng.standard_normal((n, d)).astype(np.float32))
    queries = data[rng.integers(0, n, nq)] + 0.05 * rng.standard_normal(
        (nq, d)).astype(np.float32)

    index = sp.create_instance("BKT", "Float")
    index.set_parameter("DistCalcMethod", "L2")
    for name, value in [("BKTNumber", "1"), ("BKTKmeansK", "16"),
                        ("TPTNumber", "8"), ("TPTLeafSize", "500"),
                        ("NeighborhoodSize", "32"), ("CEF", "256"),
                        ("MaxCheckForRefineGraph", "512"),
                        ("RefineIterations", "2"),
                        ("FinalRefineSearchMode", "same"),
                        ("SearchMode", "beam")]:
        assert index.set_parameter(name, value), name
    t0 = time.time()
    index.build(data)
    print(f"[floor] build {time.time() - t0:.0f}s", flush=True)

    exact = ((queries ** 2).sum(1)[:, None] + (data ** 2).sum(1)[None, :]
             - 2.0 * queries @ data.T)
    truth = np.argsort(exact, axis=1)[:, :10]

    def recall(ids):
        return float(np.mean([
            len(set(int(v) for v in ids[q] if v >= 0)
                & set(int(v) for v in truth[q])) / 10 for q in range(nq)]))

    orig = eng.beam_width_for
    rows = []
    try:
        for mc in (512, 2048):
            for B in (1, 8, 32, 128):
                eng.beam_width_for = \
                    lambda bw, m, L, _B=B: max(1, min(_B, L))
                # warm compile at this (B, T) shape
                index.search_batch(queries, 10, max_check=mc)
                t0 = time.time()
                _, ids = index.search_batch(queries, 10, max_check=mc)
                dt = time.time() - t0
                rows.append({"max_check": mc, "B": B,
                             "recall_at_10": round(recall(ids), 4),
                             "wall_s": round(dt, 2),
                             "qps": round(nq / dt, 1)})
                print(f"[floor] mc={mc} B={B}: recall "
                      f"{rows[-1]['recall_at_10']} wall "
                      f"{rows[-1]['wall_s']}s", flush=True)
    finally:
        eng.beam_width_for = orig
    print(json.dumps({"n": n, "queries": nq, "rows": rows}), flush=True)


if __name__ == "__main__":
    main()
