"""Offline autotuner — search the recall-vs-QPS Pareto frontier, emit a
per-workload config artifact (ISSUE 17 tentpole a).

KBest (arXiv:2508.03016) tunes exactly these knobs per deployment; the
ROADMAP's "millions of users" north star means nobody hand-tunes per
tenant.  This tool closes the OFFLINE half of the loop: sweep the
candidate-budget grid against a ground-truth query set (the bench
pareto-stage measurement, Wilson CIs and all), keep the Pareto frontier,
pick the highest-QPS point whose recall CI LOWER bound clears the
declared target (the CI floor, not the point estimate — a thin query
set cannot fake health), and emit two files:

* ``autotune.ini`` — an INI fragment of ``[Index]`` Name=Value pairs a
  server applies at start ([Service] AutotuneConfig=, flowing through
  the same `set_parameter` path an operator or the online controller
  uses);
* ``autotune.json`` — full provenance: schema version, git rev, corpus
  fingerprint, the chosen point, every frontier point, and every point
  REJECTED with the reason (dominated / below the recall gate), so a
  later run can explain why the knob is what it is.

The regression gate is tools/benchdiff.py: ``--gate BASELINE.json``
diffs this run's operating point against a prior artifact's
``autotune.qps_at_slo`` / ``autotune.recall_at_10`` lines and exits
non-zero on regression — the same judgement bench CI applies.

Every knob the artifact may set is validated against the core/params
LIVE-ACTUATION REGISTRY before emission: the offline tuner honors the
same bounds contract as the online controller.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

SCHEMA_VERSION = 1
ARTIFACT_INI = "autotune.ini"
ARTIFACT_JSON = "autotune.json"


def _git_rev() -> str:
    """Short git rev of the tuned tree; 'unknown' when git is
    unavailable — never fatal (the bench.py provenance pattern)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
            capture_output=True, text=True, timeout=10)
        rev = out.stdout.strip()
        if out.returncode == 0 and rev:
            dirty = subprocess.run(
                ["git", "status", "--porcelain"], cwd=REPO,
                capture_output=True, text=True, timeout=10)
            if dirty.returncode == 0 and dirty.stdout.strip():
                rev += "-dirty"
            return rev
    except (OSError, subprocess.SubprocessError):
        pass
    return "unknown"


def fingerprint_array(arr: np.ndarray) -> str:
    """Corpus fingerprint: sha256 over dtype/shape/bytes — the artifact
    binds to the data it was tuned against."""
    h = hashlib.sha256()
    h.update(str(arr.dtype).encode())
    h.update(str(arr.shape).encode())
    h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()[:16]


# --------------------------------------------------------------- measure


def measure_point(index, queries, truth, k: int,
                  max_check: Optional[int] = None,
                  max_queries: int = 512) -> dict:
    """One operating point: warm, time a batch, score recall with a
    Wilson CI (the bench pareto-stage measurement).  `max_check=None`
    measures the index AS CONFIGURED (the replay path)."""
    from sptag_tpu.utils import qualmon

    qn = min(len(queries), max_queries)
    kw = {} if max_check is None else {"max_check": int(max_check)}
    index.search_batch(queries[:qn], k, **kw)               # warm
    t0 = time.perf_counter()
    _, ids = index.search_batch(queries[:qn], k, **kw)
    dt = time.perf_counter() - t0
    rec = qualmon.recall_at_k(ids, truth[:qn], k)
    lo, hi = qualmon.wilson(rec * qn * k, qn * k)
    point = {
        "qps": round(qn / dt, 1),
        "recall_at_10": round(rec, 4),
        "ci": [round(lo, 4), round(hi, 4)],
        "queries": qn,
        "non_default_params": dict(index.params.non_default_items()),
    }
    if max_check is not None:
        point["max_check"] = int(max_check)
    return point


def sweep(index, queries, truth, k: int, grid: List[int],
          deadline: Optional[float] = None,
          max_queries: int = 512) -> Tuple[List[dict], List[int]]:
    """Measure every MaxCheck on `grid` (bounds-checked against the
    live-actuation registry); returns (points, dropped) where dropped
    holds grid values skipped for the wall-clock deadline — caps are
    recorded, never silent (the bench stage-budget discipline)."""
    from sptag_tpu.core import params as core_params

    points, dropped = [], []
    for mc in grid:
        if deadline is not None and time.monotonic() >= deadline:
            dropped.append(int(mc))
            continue
        bounded = int(core_params.clamp_actuation("MaxCheck", mc))
        points.append(measure_point(index, queries, truth, k,
                                    max_check=bounded,
                                    max_queries=max_queries))
    return points, dropped


def pareto_frontier(points: List[dict]
                    ) -> Tuple[List[dict], List[dict]]:
    """Split measured points into the Pareto frontier and the dominated
    rest; dominated points carry the reason (which point beat them)."""
    frontier, rejected = [], []
    for p in points:
        dom = next(
            (q for q in points if q is not p
             and q["qps"] >= p["qps"]
             and q["recall_at_10"] >= p["recall_at_10"]
             and (q["qps"] > p["qps"]
                  or q["recall_at_10"] > p["recall_at_10"])), None)
        if dom is None:
            frontier.append(p)
        else:
            rejected.append(dict(
                p, reason="dominated by max_check=%s"
                % dom.get("max_check", "?")))
    return frontier, rejected


def choose(frontier: List[dict], recall_target: float
           ) -> Tuple[Optional[dict], List[dict]]:
    """Highest-QPS frontier point whose Wilson LOWER bound clears the
    recall target; frontier points failing the gate join the rejected
    list with the reason.  No point clears the gate -> the highest-
    recall point wins (the artifact says so via `gate_met`: a tuner
    that silently under-delivers recall is worse than no tuner)."""
    ok = [p for p in frontier if p["ci"][0] >= recall_target]
    rejected = [dict(p, reason="ci_lo %.4f < recall target %.4f"
                     % (p["ci"][0], recall_target))
                for p in frontier if p["ci"][0] < recall_target]
    if ok:
        chosen = dict(max(ok, key=lambda p: p["qps"]), gate_met=True)
    elif frontier:
        chosen = dict(max(frontier, key=lambda p: p["recall_at_10"]),
                      gate_met=False)
        rejected = [p for p in rejected
                    if p.get("max_check") != chosen.get("max_check")]
    else:
        chosen = None
    return chosen, rejected


# ------------------------------------------------------------------ emit


def emit(out_dir: str, chosen: dict, frontier: List[dict],
         rejected: List[dict], recall_target: float,
         corpus_fingerprint: str, extra: Optional[dict] = None
         ) -> Dict[str, str]:
    """Write autotune.ini + autotune.json into `out_dir`; returns their
    paths.  Artifact knobs are validated against the live-actuation
    registry (UnknownActuationError surfaces a tuner bug at emission,
    not at some later server start)."""
    from sptag_tpu.core import params as core_params

    knobs: Dict[str, object] = {}
    if "max_check" in chosen:
        knobs["MaxCheck"] = int(core_params.clamp_actuation(
            "MaxCheck", chosen["max_check"]))
    for name, value in (chosen.get("knobs") or {}).items():
        knobs[core_params.actuation_spec(name).name] = value
    os.makedirs(out_dir, exist_ok=True)
    ini_path = os.path.join(out_dir, ARTIFACT_INI)
    json_path = os.path.join(out_dir, ARTIFACT_JSON)
    with open(ini_path, "w", encoding="utf-8") as f:
        f.write("; emitted by tools/autotune.py — apply via [Service] "
                "AutotuneConfig=\n[Index]\n")
        for name, value in knobs.items():
            f.write("%s=%s\n" % (name, value))
    provenance = {
        "schema_version": SCHEMA_VERSION,
        "tool": "tools/autotune.py",
        "created_unix": round(time.time(), 1),
        "git_rev": _git_rev(),
        "corpus_fingerprint": corpus_fingerprint,
        "recall_target": recall_target,
        "knobs": knobs,
        "chosen": chosen,
        "frontier": frontier,
        "rejected": rejected,
    }
    provenance.update(extra or {})
    with open(json_path, "w", encoding="utf-8") as f:
        json.dump(provenance, f, indent=2, sort_keys=True)
        f.write("\n")
    return {"ini": ini_path, "json": json_path}


def replay(index, queries, truth, k: int, ini_path: str,
           max_queries: int = 512) -> dict:
    """Apply an emitted artifact to `index` through the SERVE-path
    helper (service.apply_autotune_artifact — the exact code a real
    server start runs) and measure at the applied operating point."""
    from sptag_tpu.serve import service as service_mod

    ctx = service_mod.ServiceContext()
    ctx.add_index("main", index)
    applied = service_mod.apply_autotune_artifact(ctx, ini_path)
    out = measure_point(index, queries, truth, k,
                        max_queries=max_queries)
    out["applied_params"] = applied
    return out


def gate(current_point: dict, baseline_json: str) -> Tuple[bool, List[str]]:
    """Benchdiff the replayed operating point against a prior
    autotune.json (or bench artifact); returns (ok, report lines)."""
    from tools import benchdiff

    with open(baseline_json, "r", encoding="utf-8") as f:
        baseline = json.load(f)
    if "autotune" not in baseline and "chosen" in baseline:
        # a bare autotune.json: lift its chosen point into the bench
        # artifact shape benchdiff's dotted paths expect
        baseline = {"schema_version": baseline.get("schema_version", 0),
                    "autotune": {
                        "qps_at_slo": baseline["chosen"].get("qps"),
                        "recall_at_10":
                            baseline["chosen"].get("recall_at_10")}}
    current = {"schema_version": baseline.get("schema_version", 0),
               "autotune": {
                   "qps_at_slo": current_point.get("qps"),
                   "recall_at_10": current_point.get("recall_at_10")}}
    verdicts, notes = benchdiff.diff(baseline, current)
    lines = list(notes)
    ok = True
    for v in verdicts:
        lines.append("%-28s %12s -> %12s  %s" % (
            v.metric.path, v.base, v.cur, v.status))
        ok = ok and v.status != "REGRESSED"
    return ok, lines


# ------------------------------------------------------------------- CLI


def _build_corpus(algo: str, n: int, dim: int, n_queries: int, k: int,
                  seed: int):
    """Synthetic workload: corpus + queries + exact truth (the bench
    clustered-blobs shape keeps the sweep's recall curve non-trivial)."""
    import sptag_tpu as sp

    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((max(8, n // 128), dim)) * 4.0
    assign = rng.integers(0, len(centers), size=n)
    data = (centers[assign]
            + rng.standard_normal((n, dim))).astype(np.float32)
    queries = (centers[rng.integers(0, len(centers), size=n_queries)]
               + rng.standard_normal((n_queries, dim))).astype(np.float32)
    index = sp.create_instance(algo, "Float")
    index.set_parameter("DistCalcMethod", "L2")
    index.build(data)
    _, truth = index.exact_search_batch(queries, k)
    return index, data, queries, np.asarray(truth)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="offline recall-vs-QPS autotuner (ISSUE 17)")
    ap.add_argument("--out", required=True,
                    help="artifact output directory")
    ap.add_argument("--algo", default="BKT")
    ap.add_argument("--corpus", type=int, default=20000)
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--queries", type=int, default=256)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--recall-target", type=float, default=0.9)
    ap.add_argument("--grid", default="256,512,1024,2048,4096,8192",
                    help="comma-separated MaxCheck sweep")
    ap.add_argument("--budget-s", type=float, default=300.0,
                    help="wall-clock budget for the sweep")
    ap.add_argument("--gate", default="",
                    help="baseline autotune.json/bench.json to "
                    "benchdiff the replayed point against")
    args = ap.parse_args(argv)

    grid = [int(t) for t in args.grid.split(",") if t.strip()]
    index, data, queries, truth = _build_corpus(
        args.algo, args.corpus, args.dim, args.queries, args.k,
        args.seed)
    deadline = time.monotonic() + args.budget_s
    points, dropped = sweep(index, queries, truth, args.k, grid,
                            deadline=deadline)
    frontier, dominated = pareto_frontier(points)
    chosen, gated_out = choose(frontier, args.recall_target)
    if chosen is None:
        print("autotune: no measurable points", file=sys.stderr)
        return 2
    paths = emit(args.out, chosen, frontier, dominated + gated_out,
                 args.recall_target, fingerprint_array(data),
                 extra={"algo": args.algo, "k": args.k,
                        "grid": grid, "grid_dropped": dropped})
    rep = replay(index, queries, truth, args.k, paths["ini"])
    print("autotune: chose MaxCheck=%s qps=%.1f recall@%d=%.4f "
          "(gate_met=%s) -> %s"
          % (chosen.get("max_check"), rep["qps"], args.k,
             rep["recall_at_10"], chosen.get("gate_met"),
             paths["ini"]))
    if args.gate:
        ok, lines = gate(rep, args.gate)
        print("\n".join(lines))
        if not ok:
            print("autotune: REGRESSED vs %s" % args.gate,
                  file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
