"""Direction-B A/B harness: build with THIS framework, save in the
reference folder format, and score the REFERENCE's own compiled searcher
(tests/fixtures/indexsearcher, built from
/root/reference/AnnService/src/IndexSearcher/main.cpp:66-228) over the
saved index.  This is the round-3 continuation protocol
(reports/AB_REFERENCE.md) as a repeatable script instead of an ad-hoc
drive — used round 4 to validate the FinalRefineSearchMode guardrail
(VERDICT item 10) and the exact int16 accumulation (VERDICT item 5).

Prints one JSON line: {"recall": {maxcheck: recall}, ...}.

Usage:
  python tools/ab_direction_b.py --algo BKT --value-type Float \
      --metric L2 --n 10000 --d 32 --nq 100 --k 10 --maxcheck 512#2048 \
      [--set Name=Value ...]
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def find_or_build_searcher() -> str:
    """The compiled reference indexsearcher: reuse /tmp/refbin if a prior
    session left it, else compile it from /root/reference (the fixtures
    README g++ recipe — no Boost needed for the core+searcher sources)."""
    cached = "/tmp/refbin/indexsearcher"
    if os.path.exists(cached):
        return cached
    os.makedirs("/tmp/refbin", exist_ok=True)
    r = "/root/reference/AnnService"
    import glob

    srcs = sum((glob.glob(os.path.join(r, p)) for p in (
        "src/Core/*.cpp", "src/Core/Common/*.cpp", "src/Core/BKT/*.cpp",
        "src/Core/KDT/*.cpp", "src/Helper/*.cpp",
        "src/Helper/VectorSetReaders/*.cpp", "src/IndexSearcher/*.cpp")),
        [])
    subprocess.run(["g++", "-std=c++14", "-O3", "-march=native",
                    "-fopenmp", "-DNDEBUG", f"-I{r}", "-o", cached]
                   + srcs, check=True, timeout=900)
    return cached


def make_corpus(n, d, nq, seed, value_type, metric):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((64, d)).astype(np.float32) * 3.0
    data = (centers[rng.integers(0, 64, n)]
            + rng.standard_normal((n, d)).astype(np.float32))
    queries = (centers[rng.integers(0, 64, nq)]
               + rng.standard_normal((nq, d)).astype(np.float32))
    if value_type == "Float":
        return data, queries
    scale = {"Int8": 100.0, "UInt8": 40.0, "Int16": 3000.0}[value_type]
    dt = {"Int8": np.int8, "UInt8": np.uint8, "Int16": np.int16}[value_type]
    if value_type == "UInt8":
        data, queries = data + 4.0, queries + 4.0     # shift into range
    return ((data * scale / 8).astype(dt), (queries * scale / 8).astype(dt))


def exact_truth(stored, queries, k, metric, base):
    """Truth over the STORED rows under the reference's exact convention
    (integer ``base^2 - dot`` for int cosine; squared L2 otherwise)."""
    s = stored.astype(np.int64 if stored.dtype.kind in "iu" else np.float64)
    q = queries.astype(s.dtype)
    if metric == "Cosine":
        sim = q @ s.T
        idx = np.argpartition(-sim, k, axis=1)[:, :k]
        row = np.take_along_axis(-sim, idx, axis=1)
    else:
        d = ((s ** 2).sum(1)[None, :].astype(np.float64)
             - 2.0 * (q @ s.T).astype(np.float64)
             + (q ** 2).sum(1)[:, None].astype(np.float64))
        idx = np.argpartition(d, k, axis=1)[:, :k]
        row = np.take_along_axis(d, idx, axis=1)
    order = np.argsort(row, axis=1, kind="stable")
    return np.take_along_axis(idx, order, axis=1)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--algo", default="BKT")
    ap.add_argument("--value-type", default="Float")
    ap.add_argument("--metric", default="L2")
    ap.add_argument("--n", type=int, default=10000)
    ap.add_argument("--d", type=int, default=32)
    ap.add_argument("--nq", type=int, default=100)
    ap.add_argument("--k", type=int, default=10)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--maxcheck", default="512#2048")
    ap.add_argument("--set", action="append", default=[],
                    help="extra Name=Value index parameters")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    import sptag_tpu as sp

    data, queries = make_corpus(args.n, args.d, args.nq, args.seed,
                                args.value_type, args.metric)

    index = sp.create_instance(args.algo, args.value_type)
    index.set_parameter("DistCalcMethod", args.metric)
    # the round-3 A/B knob set (reports/AB_REFERENCE.md direction-B
    # protocol) so numbers stay comparable across rounds
    tree_knob = "BKTNumber" if args.algo == "BKT" else "KDTNumber"
    defaults = [(tree_knob, "1"), ("BKTKmeansK", "32"),
                ("TPTNumber", "8"), ("NeighborhoodSize", "32"),
                ("CEF", "256"), ("MaxCheckForRefineGraph", "512"),
                ("RefineIterations", "2"), ("MaxCheck", "2048")]
    if args.algo != "BKT":
        defaults = [kv for kv in defaults if kv[0] != "BKTKmeansK"]
    for name, value in defaults:
        index.set_parameter(name, value)
    for kv in args.set:
        name, _, value = kv.partition("=")
        if not index.set_parameter(name, value):
            raise SystemExit(f"unknown parameter {name}")
    index.build(data)

    with tempfile.TemporaryDirectory() as tmp:
        folder = os.path.join(tmp, "idx")
        index.save_index(folder)

        # the reference normalizes queries itself for cosine; feed RAW
        # values.  Truth is over the STORED rows (the save is the corpus
        # the reference searches).
        import sptag_tpu.io.format as fmt

        with open(os.path.join(folder, "vectors.bin"), "rb") as f:
            stored = fmt.read_matrix(f, data.dtype)
        if args.metric == "Cosine":
            from sptag_tpu.ops.distance import normalize
            base = int(index.base)
            qn = (normalize(queries, base) if base != 1
                  else queries / np.maximum(
                      np.linalg.norm(queries.astype(np.float64), axis=1,
                                     keepdims=True), 1e-9))
            truth = exact_truth(stored, qn, args.k, "Cosine", base)
        else:
            truth = exact_truth(stored, queries, args.k, "L2", 1)

        qfile = os.path.join(tmp, "queries.tsv")
        with open(qfile, "w") as f:
            for i, row in enumerate(queries):
                vals = "|".join(str(v) for v in row.tolist())
                f.write(f"q{i}\t{vals}\n")
        tfile = os.path.join(tmp, "truth.txt")
        with open(tfile, "w") as f:
            for row in truth:
                f.write(" ".join(str(int(v)) for v in row) + "\n")

        out = subprocess.run(
            [find_or_build_searcher(), folder, f"Index.QueryFile={qfile}",
             f"Index.TruthFile={tfile}", f"Index.K={args.k}",
             f"Index.MaxCheck={args.maxcheck}",
             f"Index.NumBatchQuerys={args.nq}"],
            capture_output=True, text=True, timeout=600, cwd=tmp)

    recalls = {}
    for line in out.stdout.splitlines():
        parts = line.split("\t")
        if len(parts) >= 5 and parts[0].strip().isdigit():
            try:
                recalls[int(parts[0])] = float(parts[4])
            except ValueError:
                pass
    print(json.dumps({
        "algo": args.algo, "value_type": args.value_type,
        "metric": args.metric, "n": args.n, "d": args.d,
        "recall": recalls, "params": args.set,
        "searcher_rc": out.returncode,
        "stderr_tail": out.stderr.strip()[-200:],
    }))


if __name__ == "__main__":
    main()
