"""Pre-build the strong-graph BKT index for the bench's beam headline.

VERDICT r4 item 2: the reference-parity beam mode must reach >=0.95
recall on the 200k bench corpus.  reports/MAXCHECK_SWEEP.md measured the
plateau as a BUILD-budget artifact — the bench cache's speed knobs (CEF
256, refine budget 512) starve the graph of cross-block edges; the same
engine over a strong build (TPT 16, CEF 512, refine budget 2048, grouped
refine) reached 0.9918 @ MaxCheck 2048 on 100k.

This tool builds that strong index for the bench corpus (hours of CPU
cold — far outside the driver's bench envelope, hence out-of-band) into
`bench.strong_cache_folder(n)`; bench.py's beam stage loads it when
present (`beam_graph: "strong"` in the JSON line) and falls back to the
headline index otherwise.  The build is resumable (SPTAG_TPU_BUILD_CKPT
stage checkpoints) so a kill restarts at the first incomplete stage.

Usage: python tools/strong_beam_build.py [n]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> None:
    import jax

    # default CPU (the out-of-band pre-build host); the watcher's chip
    # stage sets STRONG_BEAM_PLATFORM=tpu to measure QPS on the real chip
    platform = os.environ.get("STRONG_BEAM_PLATFORM", "cpu")
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import sptag_tpu as sp
    from bench import (CACHE_DIR, _STRONG_GRAPH_PARAMS, l2_truth,
                       make_dataset, recall_at_k, strong_cache_folder)

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 200_000
    folder = strong_cache_folder(n)
    data, queries = make_dataset(n=n, nq=1000)

    os.environ.setdefault("SPTAG_TPU_BUILD_CKPT",
                          os.path.join(CACHE_DIR, "build_ckpt"))
    if os.path.exists(os.path.join(folder, "indexloader.ini")):
        index = sp.load_index(folder)
        build_s, cached = 0.0, True
    else:
        index = sp.create_instance("BKT", "Float")
        index.set_parameter("DistCalcMethod", "L2")
        index.set_parameter("BKTNumber", "1")
        index.set_parameter("BKTKmeansK", "32")
        for name, value in _STRONG_GRAPH_PARAMS:
            assert index.set_parameter(name, value), name
        t0 = time.time()
        index.build(data)
        build_s = time.time() - t0
        index.save_index(folder)
        cached = False
    print(f"[strong] build {build_s:.0f}s cached={cached}", flush=True)

    # recall check (platform-independent); QPS labeled by platform
    index.set_parameter("SearchMode", "beam")
    truth = l2_truth(data, queries, 10)
    out = {"n": n, "build_s": round(build_s, 1), "cached": cached,
           "folder": folder, "platform": platform}
    for mc in (2048, 8192):
        _ = index.search_batch(queries, 10, max_check=mc)   # warm/compile
        t0 = time.time()
        _, ids = index.search_batch(queries, 10, max_check=mc)
        dt = time.time() - t0
        out[f"beam_recall_mc{mc}"] = round(
            recall_at_k(ids, truth, 10), 4)
        out[f"beam_qps_mc{mc}"] = round(len(queries) / dt, 1)
        print(f"[strong] mc={mc}: recall "
              f"{out[f'beam_recall_mc{mc}']} qps "
              f"{out[f'beam_qps_mc{mc}']}", flush=True)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
