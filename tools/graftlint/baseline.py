"""Baseline (accepted-findings) file: load, validate, match.

`baseline.toml` holds findings that were reviewed and ACCEPTED — each
entry must carry a one-line justification, so the suppression is a
documented decision, not a mute button.  Matching is on (rule, path,
symbol[, contains]) rather than line number: unrelated edits that shift
lines must not invalidate a suppression, while moving the flagged code to
a different function (a real change) must.

The file is a small TOML subset — array-of-tables `[[suppress]]` entries
with string values — parsed here without a TOML dependency (this
python has neither tomllib (3.11+) nor tomli, and the container's
package set is frozen):

    [[suppress]]
    rule = "GL203"
    path = "sptag_tpu/algo/engine.py"
    symbol = "_beam_search_kernel"          # optional; "" = any
    contains = "per shape"                  # optional message substring
    justification = "intentional shape specialization; one compile per P"
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from tools.graftlint.core import Finding


@dataclasses.dataclass
class Suppression:
    rule: str
    path: str
    symbol: str = ""
    contains: str = ""
    justification: str = ""
    lineno: int = 0          # in the baseline file, for diagnostics
    hits: int = 0

    def matches(self, f: Finding) -> bool:
        if f.rule != self.rule or f.path != self.path:
            return False
        if self.symbol and f.symbol != self.symbol:
            return False
        if self.contains and self.contains not in f.message:
            return False
        return True


class BaselineError(ValueError):
    pass


def parse_baseline(text: str, origin: str = "baseline.toml"
                   ) -> List[Suppression]:
    entries: List[Suppression] = []
    current: Optional[Suppression] = None
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[suppress]]":
            current = Suppression("", "", lineno=lineno)
            entries.append(current)
            continue
        if line.startswith("["):
            raise BaselineError(
                f"{origin}:{lineno}: unsupported table {line!r} "
                "(only [[suppress]] entries)")
        key, sep, value = (p.strip() for p in line.partition("="))
        if not sep:
            raise BaselineError(
                f"{origin}:{lineno}: expected `key = \"value\"`")
        if current is None:
            raise BaselineError(
                f"{origin}:{lineno}: key outside a [[suppress]] entry")
        # find the first UNESCAPED closing quote (an inline comment may
        # follow it; escaped quotes inside the string are skipped)
        if not value.startswith('"'):
            raise BaselineError(
                f"{origin}:{lineno}: value must be a double-quoted string")
        closing = None
        i = 1
        while i < len(value):
            if value[i] == '"' and value[i - 1] != "\\":
                closing = i
                break
            i += 1
        if closing is None:
            raise BaselineError(
                f"{origin}:{lineno}: unterminated string value")
        literal = value[1:closing].replace('\\"', '"')
        if key not in ("rule", "path", "symbol", "contains",
                       "justification"):
            raise BaselineError(f"{origin}:{lineno}: unknown key {key!r}")
        setattr(current, key, literal)
    for e in entries:
        if not e.rule or not e.path:
            raise BaselineError(
                f"{origin}:{e.lineno}: entry needs `rule` and `path`")
        if not e.justification.strip():
            raise BaselineError(
                f"{origin}:{e.lineno}: entry for {e.rule} at {e.path} has "
                "no justification — every accepted finding must say why")
    return entries


def load_baseline(path: str) -> List[Suppression]:
    with open(path, encoding="utf-8") as f:
        return parse_baseline(f.read(), origin=path)


def apply_baseline(findings: List[Finding],
                   suppressions: List[Suppression]
                   ) -> Tuple[List[Finding], List[Finding]]:
    """-> (unsuppressed, suppressed).  Increments `hits` so the caller can
    report stale entries (zero hits = the accepted finding is gone —
    prune it)."""
    unsuppressed: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        hit = None
        for s in suppressions:
            if s.matches(f):
                hit = s
                break
        if hit is None:
            unsuppressed.append(f)
        else:
            hit.hits += 1
            suppressed.append(f)
    return unsuppressed, suppressed
