"""GL3xx — concurrency lint.

The serving design is "immutable device snapshots + one writer lock"
(docs/DESIGN.md §3): every attribute the background machinery shares is
assigned under `self._lock` (the lock inventory seeded from
serve/server.py, serve/client.py, core/index.py, algo/bkt.py,
utils/threadpool.py).  An unlocked assignment to one of those attributes
from a thread-entry path is a data race that only shows up under heavy
traffic — the most expensive class of bug to bisect from a bench number.

Rules:

* GL301 — an attribute that is elsewhere assigned under a `with
  self.<lock>:` block is assigned WITHOUT the lock in a method reachable
  from a thread entry point (`threading.Thread(target=...)`, a
  `ThreadPool.add(...)` job, or `run_in_executor`).
* GL302 — a closure (lambda or nested def) created inside a `for` loop
  captures the loop variable by reference and is handed to a thread/pool
  API: by the time the job runs, every closure sees the LAST iteration's
  value.  Bind it as a default argument instead.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.graftlint.core import (
    Finding,
    FunctionInfo,
    ModuleInfo,
    Project,
    _dotted,
    statements_under_with,
)

RULES = {
    "GL301": "lock-protected attribute assigned without the lock in a "
             "thread-entry-reachable method",
    "GL302": "late-binding loop-variable capture in a closure handed to "
             "a thread/pool API",
}

#: call names that take a job/target callable
_SPAWN_CALLS = {"add", "submit", "apply_async", "run_in_executor", "Thread"}
_LOCK_HINTS = ("lock",)


def _lock_attr_names(cls_methods: List[FunctionInfo]) -> Set[str]:
    """Attribute names used as `with self.<name>:` context managers whose
    name smells like a lock (`_lock`, `_wlock`, ...)."""
    names: Set[str] = set()
    for m in cls_methods:
        for node in ast.walk(m.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    d = _dotted(item.context_expr)
                    if d and d.startswith("self."):
                        leaf = d.split(".")[-1]
                        if any(h in leaf.lower() for h in _LOCK_HINTS):
                            names.add(leaf)
    return names


def _self_attr_assigns(fn: FunctionInfo):
    """(attr_name, lineno) for every `self.X = ...` / `self.X op= ...`."""
    for node in ast.walk(fn.node):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Attribute) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == "self":
                yield tgt.attr, node.lineno
            elif isinstance(tgt, ast.Tuple):
                for el in tgt.elts:
                    if isinstance(el, ast.Attribute) and \
                            isinstance(el.value, ast.Name) and \
                            el.value.id == "self":
                        yield el.attr, node.lineno


def _guarded_attrs(cls_methods: List[FunctionInfo],
                   lock_names: Set[str]) -> Set[str]:
    """Attributes assigned at least once under a lock in this class."""
    guarded: Set[str] = set()
    for m in cls_methods:
        held = statements_under_with(m, sorted(lock_names))
        for attr, line in _self_attr_assigns(m):
            if line in held:
                guarded.add(attr)
    return guarded


def _thread_entry_methods(cls_methods: List[FunctionInfo]) -> Set[str]:
    """Method names handed to Thread(target=...) / pool.add / submit /
    run_in_executor within THIS class's own methods (a spawn in class A
    must not mark a same-named method in class B), expanded over
    self-calls."""
    entries: Set[str] = set()
    for m in cls_methods:
        for node in ast.walk(m.node):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            leaf = d.split(".")[-1] if d else ""
            if leaf not in _SPAWN_CALLS:
                continue
            cands = list(node.args) + [kw.value for kw in node.keywords
                                       if kw.arg in ("target", "func",
                                                     "fn")]
            for cand in cands:
                cd = _dotted(cand)
                if cd and cd.startswith("self."):
                    entries.add(cd.split(".")[-1])
    # expand over self.method() calls from entry methods (fixpoint)
    by_name: Dict[str, FunctionInfo] = {m.name: m for m in cls_methods}
    changed = True
    while changed:
        changed = False
        for name in list(entries):
            m = by_name.get(name)
            if m is None:
                continue
            for node in ast.walk(m.node):
                if isinstance(node, ast.Call):
                    d = _dotted(node.func)
                    if d and d.startswith("self."):
                        callee = d.split(".")[-1]
                        if callee in by_name and callee not in entries:
                            entries.add(callee)
                            changed = True
    return entries


def _check_gl301(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for cls in mod.classes():
        method_nodes = {n for n in cls.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
        methods = [f for f in mod.functions if f.node in method_nodes]
        if not methods:
            continue
        lock_names = _lock_attr_names(methods)
        if not lock_names:
            continue
        guarded = _guarded_attrs(methods, lock_names)
        entries = _thread_entry_methods(methods)
        for m in methods:
            if m.name not in entries or m.name == "__init__":
                continue
            held = statements_under_with(m, sorted(lock_names))
            for attr, line in _self_attr_assigns(m):
                if attr in guarded and line not in held:
                    out.append(Finding(
                        "GL301", mod.relpath, line,
                        f"`self.{attr}` is lock-protected elsewhere in "
                        f"`{cls.name}` but assigned here without "
                        f"holding {'/'.join(sorted(lock_names))} on a "
                        "thread-entry path", m.qualname))
    return out


def _loop_targets(loop: ast.For) -> Set[str]:
    return {n.id for n in ast.walk(loop.target)
            if isinstance(n, ast.Name)}


def _free_names(fn_node: ast.AST) -> Set[str]:
    """Names read inside a lambda/def, minus its own params and locals."""
    bound: Set[str] = set()
    args = fn_node.args
    for p in args.posonlyargs + args.args + args.kwonlyargs:
        bound.add(p.arg)
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)
    body = fn_node.body if isinstance(fn_node.body, list) else [fn_node.body]
    reads: Set[str] = set()
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Store):
                    bound.add(n.id)
                else:
                    reads.add(n.id)
    return reads - bound


def _check_gl302(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for fn in mod.functions:
        for node in ast.walk(fn.node):
            if not isinstance(node, (ast.For, ast.AsyncFor)):
                continue
            loop_vars = _loop_targets(node)
            if not loop_vars:
                continue
            for inner in ast.walk(node):
                if not isinstance(inner, ast.Call):
                    continue
                d = _dotted(inner.func)
                leaf = d.split(".")[-1] if d else ""
                if leaf not in _SPAWN_CALLS:
                    continue
                cands = list(inner.args) + \
                    [kw.value for kw in inner.keywords
                     if kw.arg in ("target", "func", "fn")]
                for cand in cands:
                    if isinstance(cand, ast.Lambda):
                        captured = _free_names(cand) & loop_vars
                        if captured:
                            out.append(Finding(
                                "GL302", mod.relpath, cand.lineno,
                                "lambda handed to "
                                f"`{leaf}` captures loop variable(s) "
                                f"{sorted(captured)} by reference — every "
                                "job sees the final iteration's value "
                                "(bind via a default argument)",
                                fn.qualname))
                    elif isinstance(cand, ast.Name):
                        # nested def passed by name: find it in the loop
                        for sub in mod.functions:
                            if sub.name == cand.id and sub.parent is fn \
                                    and sub.node.lineno >= node.lineno:
                                captured = _free_names(sub.node) & loop_vars
                                if captured:
                                    out.append(Finding(
                                        "GL302", mod.relpath,
                                        sub.node.lineno,
                                        f"closure `{cand.id}` handed to "
                                        f"`{leaf}` captures loop "
                                        f"variable(s) {sorted(captured)} "
                                        "by reference (bind via a "
                                        "default argument)", fn.qualname))
    return out


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules.values():
        out.extend(_check_gl301(mod))
        out.extend(_check_gl302(mod))
    return out
