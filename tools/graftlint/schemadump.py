"""graftlint --schema-dump — the GL10xx runtime complement.

Same contract as locksan (GL8xx) and tracesan (GL9xx): the static pass
proves the producer/consumer name graph is closed over the *source*;
this harness proves it is closed over the *running system*.  It boots a
search server + aggregator in-process with every telemetry knob armed
(timeline, canary, SLO objectives, qualmon shadow audit, flight
recorder, metrics HTTP), drives real client traffic plus canary probes
through both tiers, forces a timeline tick, scrapes /metrics and every
registered /debug route, and then diffs the live exposition against the
static ObsModel in BOTH directions:

* live → model: every metric, family, timeline series, flight-recorder
  kind, and HTTP route the armed system actually exposes must be
  modeled (a dynamically minted name the static harvest cannot see is
  exactly how the `iter_cost1` gflops attribution died silently);
* model → live: every name a static *consumer* reads — the SLO
  objective sources, the controller inputs — must actually receive
  data in the armed scenario (the PR 15 bug: the SLO engine read
  `aggregator.requests.rate`, which no live tick ever produced), plus
  a curated must-emit core of the serve path; and every statically
  registered route must answer the scrape.

`python -m tools.graftlint --schema-dump` runs it standalone (exit 0 =
empty diff both directions); tests/test_obsgraph.py ships the same
check as a tier-1 test so name drift cannot land.
"""

from __future__ import annotations

import json
import re
import sys
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

#: routes whose handler legitimately answers non-200 in the armed
#: harness (no device traces recorded -> 404); liveness = "the handler
#: ran and answered", not "content exists"
_NON_200_OK = {"/debug/devicetrace"}

#: timeline keys the harness itself mints (test-local series)
_HARNESS_PREFIX = "schemadump."


class SchemaDiff:
    """The two-direction diff result."""

    def __init__(self) -> None:
        self.live_unmodeled: List[str] = []   # live name, no static producer
        self.model_unemitted: List[str] = []  # static must-emit, not live

    @property
    def clean(self) -> bool:
        return not self.live_unmodeled and not self.model_unemitted

    def format(self) -> str:
        lines = []
        for item in self.live_unmodeled:
            lines.append(f"live-but-unmodeled: {item}")
        for item in self.model_unemitted:
            lines.append(f"modeled-but-never-emitted: {item}")
        return "\n".join(lines)


def _http_get(port: int, path: str) -> Tuple[int, str]:
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    conn.request("GET", path)
    resp = conn.getresponse()
    body = resp.read().decode()
    conn.close()
    return resp.status, body


class _LoopThread(threading.Thread):
    """Standalone copy of tests/conftest.py::ServerThread — this module
    must run without tests/ on sys.path (bench.py keeps the same
    standalone variant for the same reason).  The stored boot-task
    reference is load-bearing: see the conftest comment."""

    def __init__(self, server) -> None:
        super().__init__(daemon=True,
                         name=f"schemadump-loop-{type(server).__name__}")
        self.server = server
        self.addr: Optional[Tuple[str, int]] = None
        self.loop = None
        self._ready = threading.Event()

    def run(self) -> None:
        import asyncio

        self.loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self.loop)

        async def boot():
            self.addr = await self.server.start("127.0.0.1", 0)
            self._ready.set()

        self._boot_task = self.loop.create_task(boot())
        self.loop.run_forever()

    def wait_ready(self, timeout: float = 60.0) -> Tuple[str, int]:
        if not self._ready.wait(timeout):
            raise RuntimeError("server failed to boot within %ss" % timeout)
        return self.addr

    def stop(self) -> None:
        import asyncio

        if self.loop is None:
            return
        fut = asyncio.run_coroutine_threadsafe(self.server.stop(),
                                               self.loop)
        try:
            fut.result(timeout=10)
        except Exception:                                # noqa: BLE001
            pass
        self.loop.call_soon_threadsafe(self.loop.stop)
        self.join(timeout=10)


def _wait(predicate, deadline_s: float, interval_s: float = 0.05) -> bool:
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if predicate():
            return True
        time.sleep(interval_s)
    return False


def _strip_label(series_key: str) -> str:
    return series_key.split("{", 1)[0]


def _base_metric(series_key: str) -> str:
    """Timeline derivation key -> its base registry metric name."""
    name = _strip_label(series_key)
    for suffix in (".rate", ".p50_ms", ".p99_ms"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def collect_live(metrics_mod, timeline_mod, flightrec_mod, families
                 ) -> Dict[str, Set[str]]:
    """Structured live-name collection — the dotted-name surfaces the
    static model speaks, not the lossy Prometheus rendering."""
    snap = metrics_mod.snapshot()
    return {
        "counters": set(snap["counters"]),
        "gauges": set(snap["gauges"]),
        "histograms": set(snap["histograms"]),
        "families": {fam.name for fam in families},
        "series": set(timeline_mod.series_names()),
        "flight_kinds": {e["kind"] for e in flightrec_mod.collect()},
    }


def diff_live_vs_model(live: Dict[str, Set[str]], model,
                       live_routes: Dict[str, int]) -> SchemaDiff:
    """Both-direction diff of a live collection against an ObsModel.
    `live_routes` maps scraped route path -> HTTP status."""
    diff = SchemaDiff()

    def modeled_metric(name: str, kind: str) -> bool:
        # xla.backend_compile[label] etc. resolve through prefixes
        return kind in model.metric_kinds(name) or \
            model.matches_prefix(name)

    for kind_key, kind in (("counters", "counter"), ("gauges", "gauge"),
                           ("histograms", "histogram")):
        for name in sorted(live[kind_key]):
            if name.startswith(_HARNESS_PREFIX):
                continue
            if not modeled_metric(name, kind):
                diff.live_unmodeled.append(f"{kind} `{name}`")
    for name in sorted(live["families"]):
        if name not in model.families and not model.matches_prefix(name):
            diff.live_unmodeled.append(f"family `{name}`")
    bare = model.bare_series()
    for key in sorted(live["series"]):
        base = _strip_label(key)
        if base.startswith(_HARNESS_PREFIX):
            continue
        if base in bare or base in model.families \
                or base in model.timeline or model.matches_prefix(base):
            continue
        # derived keys (x.rate / x.p50_ms / x.p99_ms) of modeled metrics
        if model.metric_kinds(_base_metric(key)) \
                or model.matches_prefix(_base_metric(key)):
            continue
        diff.live_unmodeled.append(f"timeline series `{key}`")
    for kind in sorted(live["flight_kinds"]):
        if kind not in model.flight_kinds:
            diff.live_unmodeled.append(f"flightrec kind `{kind}`")
    for path in sorted(live_routes):
        if path not in model.routes:
            diff.live_unmodeled.append(f"route `{path}`")

    # ---- model -> live ---------------------------------------------------
    # every statically harvested timeline READ (the SLO objective
    # sources + controller inputs) must have received live data — this
    # direction is the PR 15 regression test
    for name in sorted({n for n, _site in model.timeline_reads}):
        if name not in live["series"]:
            diff.model_unemitted.append(
                f"consumed timeline series `{name}` (an SLO/controller "
                "source) never received a live point")
    # curated must-emit core of the armed serve path
    for name, kind_key in (("server.requests", "counters"),
                           ("server.responses", "counters"),
                           ("canary.probes", "counters"),
                           ("aggregator.requests", "counters"),
                           ("quality.samples", "counters"),
                           ("server.request", "histograms"),
                           ("aggregator.request", "histograms")):
        if name not in live[kind_key]:
            diff.model_unemitted.append(f"metric `{name}`")
    for fam in ("canary.recall", "slo.state", "flight.recorded",
                "quality.recall_at_k"):
        if fam not in live["families"]:
            diff.model_unemitted.append(f"family `{fam}`")
    for kind in ("request", "execute", "fanout", "merge"):
        if kind not in live["flight_kinds"]:
            diff.model_unemitted.append(f"flightrec kind `{kind}`")
    # every statically registered route answered the scrape
    for path in sorted(model.routes):
        status = live_routes.get(path)
        if status is None:
            diff.model_unemitted.append(f"route `{path}` never scraped")
        elif status != 200 and path not in _NON_200_OK:
            diff.model_unemitted.append(
                f"route `{path}` answered HTTP {status}")
    return diff


def run_schema_dump(root: str = "sptag_tpu",
                    verbose: bool = True) -> SchemaDiff:
    """Boot the armed two-tier scenario, scrape, diff.  Callers own
    process-wide telemetry state: this resets metrics/timeline/
    flightrec on entry (same convention as the locksan/tracesan
    harnesses)."""
    import tempfile

    import numpy as np

    import sptag_tpu as sp
    from sptag_tpu.serve.aggregator import (AggregatorContext,
                                            AggregatorService,
                                            RemoteServer)
    from sptag_tpu.serve.client import AnnClient
    from sptag_tpu.serve.server import SearchServer
    from sptag_tpu.serve.service import ServiceContext, ServiceSettings
    from sptag_tpu.utils import flightrec, metrics, qualmon, timeline

    from tools.graftlint import obsgraph
    from tools.graftlint.core import Project

    model = obsgraph.build_model(Project.from_tree(root))

    metrics.reset()
    timeline.reset()
    flightrec.reset()
    flightrec.configure(enabled=True)

    rng = np.random.default_rng(0)
    data = rng.standard_normal((60, 8)).astype(np.float32)
    idx = sp.create_instance("FLAT", "Float")
    idx.set_parameter("DistCalcMethod", "L2")
    idx.build(data)

    ctx = ServiceContext(ServiceSettings(default_max_result=5,
                                         canary_probes=4,
                                         metrics_port=-1))
    ctx.add_index("main", idx)
    server = SearchServer(ctx, batch_window_ms=1.0,
                          timeline_interval_ms=50.0,
                          canary_interval_ms=30.0,
                          quality_sample_rate=1.0)
    ts = _LoopThread(server)
    ts.start()
    diff = SchemaDiff()
    tg = client = None
    probe_file = tempfile.NamedTemporaryFile(
        mode="w", suffix=".txt", delete=False)
    try:
        hs, ps = ts.wait_ready(60)
        probe_file.write("$resultnum:3 " + "|".join(
            repr(float(x)) for x in data[7]) + "\n")
        probe_file.close()
        agg_ctx = AggregatorContext(
            search_timeout_s=30.0, metrics_port=-1,
            flight_recorder=True,
            timeline_interval_ms=100.0,
            slo_p99_ms=500.0, slo_availability_target=0.99,
            slo_fast_window_s=1.0, slo_slow_window_s=2.5,
            canary_interval_ms=50.0,
            canary_probe_file=probe_file.name)
        agg_ctx.servers = [RemoteServer(hs, ps)]
        agg = AggregatorService(agg_ctx)
        tg = _LoopThread(agg)
        tg.start()
        tg.wait_ready(60)

        # real (non-canary) traffic: qualmon samples only live queries
        client = AnnClient(hs, ps, timeout_s=20.0)
        client.connect()
        for i in range(4):
            q = "|".join(repr(float(x)) for x in data[3 + i])
            client.search(q)
        qualmon.drain()

        # both tiers' canaries must have probed, and at least one live
        # qualmon sample must have landed, before the scrape
        _wait(lambda: metrics.counter_value("canary.probes") >= 4
              and metrics.counter_value("quality.samples") >= 1, 30.0)
        _wait(lambda: (agg._canary is not None
                       and agg._canary.snapshot()["indexes"]
                       .get("aggregator", {}).get("probes", 0) > 0), 30.0)
        # two deterministic ticks so counter rates and family series
        # exist regardless of the samplers' own phase
        timeline.sample_now()
        time.sleep(0.25)
        timeline.sample_now()

        live_routes: Dict[str, int] = {}
        for http in (server._metrics_http, agg._metrics_http):
            if http is None:
                continue
            for path in http.routes():
                status, _body = _http_get(http.port, path)
                # prefer a 200 from either tier (e.g. /debug/slo is
                # only armed on the aggregator)
                prev = live_routes.get(path)
                if prev is None or (prev != 200 and status == 200):
                    live_routes[path] = status

        live = collect_live(metrics, timeline, flightrec,
                            metrics.collect_families())
        diff = diff_live_vs_model(live, model, live_routes)
    finally:
        if client is not None:
            client.close()
        if tg is not None:
            tg.stop()
        ts.stop()
        flightrec.configure(enabled=False)
        timeline.configure(enabled=False)

    if verbose:
        if diff.clean:
            print("schema-dump: live exposition and static ObsModel "
                  "agree (both directions)")
        else:
            print(diff.format())
            print(f"schema-dump: {len(diff.live_unmodeled)} live-but-"
                  f"unmodeled, {len(diff.model_unemitted)} modeled-but-"
                  "never-emitted")
    return diff


def main(roots: List[str]) -> int:
    root = roots[0] if roots else "sptag_tpu"
    try:
        diff = run_schema_dump(root)
    except Exception as e:                               # noqa: BLE001
        print(f"schema-dump: harness failed: {e!r}", file=sys.stderr)
        return 2
    return 0 if diff.clean else 1
