"""GL70x — interprocedural lock-order & blocking-under-lock analysis.

The serving tier holds ~15 distinct locks across serve/, core/ and
utils/, and no per-class view (GL3xx) can see a deadlock: a lock-order
inversion is by definition a property of TWO acquisition sites in
different functions — often different modules — reached through the call
graph.  This checker builds a PROJECT-WIDE lock model:

* every lock object is resolved to a canonical id — attribute locks
  (``self._lock`` in class C of module M → ``M.C._lock``, resolved
  through the class's base chain so `BKTIndex`'s inherited writer lock
  and `VectorIndex`'s are ONE lock) and module-level locks
  (``trace._lock`` → ``sptag_tpu.utils.trace._lock``);
* a lock-ACQUISITION GRAPH is built by walking every function body and
  following calls through the project call graph (including
  ``self.<attr>.<method>()`` through ``self.<attr> = Class()``
  assignments): an edge A→B means lock B is (possibly transitively)
  acquired while A is held.  Callables merely PASSED to a spawn API
  (``Thread(target=f)``, ``pool.add(f)``) deliberately do not count —
  they run later, on another thread, not under the caller's locks.

Rules:

* GL701 — a cycle in the acquisition graph (potential deadlock), reported
  once per strongly-connected component with the witness path for each
  edge; plus the degenerate case of a non-reentrant ``threading.Lock``
  re-acquired while already held (guaranteed self-deadlock).
* GL702 — a blocking call while a lock is held: socket
  sendall/recv/create_connection, ``queue.get/put`` without a timeout,
  ``Future.result()`` without a timeout, ``time.sleep``, jax's
  ``block_until_ready`` / ``device_get``, and subprocess calls.  One
  stalled holder convoys every thread behind the lock — the KBest
  serving-tail pathology.
* GL704 — a ``threading.Thread`` / ``asyncio.create_task`` handle that
  never reaches a ``join()`` / ``cancel()`` on any shutdown path in its
  module: the thread/task outlives its owner silently.  Handles appended
  to a collection are accepted when the module joins/cancels loop
  targets (the worker-list idiom); handles returned to the caller are
  the caller's responsibility.

The runtime complement is sptag_tpu/utils/locksan.py — it observes the
orders a live process actually takes; tests/test_locksan.py cross-checks
its observed graph against this module's static one.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.core import (
    Finding,
    FunctionInfo,
    ModuleInfo,
    Project,
    _dotted,
)

RULES = {
    "GL701": "lock-order cycle in the project acquisition graph "
             "(potential deadlock)",
    "GL702": "blocking call (socket/queue/Future/sleep/device-sync/"
             "subprocess) while a lock is held",
    "GL704": "thread/task handle never reaches a join/cancel on any "
             "shutdown path",
}

#: lock constructors -> reentrant?
_THREADING_CTORS = {
    "threading.Lock": False,
    "threading.RLock": True,
    "threading.Condition": True,
    "threading.Semaphore": True,
    "threading.BoundedSemaphore": True,
}
_ASYNCIO_CTORS = {"asyncio.Lock", "asyncio.Condition", "asyncio.Semaphore",
                  "asyncio.BoundedSemaphore"}
#: sptag_tpu.utils.locksan factories / classes -> reentrant?
_LOCKSAN_CTORS = {"make_lock": False, "make_rlock": True,
                  "SanLock": False, "SanRLock": True}

#: `with self.X:` where X's creation is unseen still counts as a lock
#: when the name smells like one (mirrors GL3xx)
_LOCK_NAME_HINTS = ("lock", "mutex", "cond", "sem")

_SOCKET_LEAVES = {"sendall", "sendto", "recv", "recv_into", "recvfrom",
                  "accept"}
_SUBPROCESS_LEAVES = {"run", "call", "check_call", "check_output", "Popen"}


def _resolve_target(func: ast.AST, mod: ModuleInfo) -> Optional[str]:
    """Fully-resolved dotted target of a call, through import aliases and
    from-imports: `sleep` (from time import sleep) -> "time.sleep"."""
    d = _dotted(func)
    if d is None:
        return None
    head, _, rest = d.partition(".")
    full = mod.resolve_head(head)
    if full is None:
        full = mod.from_imports.get(head)
    if full:
        return full + ("." + rest if rest else "")
    return d


def _lock_ctor(call: ast.Call, mod: ModuleInfo) -> Optional[Tuple[str, bool]]:
    """(kind, reentrant) when `call` constructs a lock object, else None.
    kind is "threading" or "asyncio"."""
    t = _resolve_target(call.func, mod)
    if t is None:
        return None
    if t in _THREADING_CTORS:
        return "threading", _THREADING_CTORS[t]
    if t in _ASYNCIO_CTORS:
        return "asyncio", True
    leaf = t.split(".")[-1]
    if leaf in _LOCKSAN_CTORS and "locksan" in t:
        return "threading", _LOCKSAN_CTORS[leaf]
    return None


def _has_timeout_kw(call: ast.Call) -> bool:
    return any(kw.arg in ("timeout", "block") for kw in call.keywords)


def _blocking_desc(call: ast.Call, mod: ModuleInfo) -> Optional[str]:
    """Human-readable description when `call` can block indefinitely (or
    for an unbounded external duration), else None."""
    t = _resolve_target(call.func, mod)
    if t is not None:
        if t == "time.sleep":
            return "time.sleep()"
        if t == "socket.create_connection":
            return "socket.create_connection()"
        parts = t.split(".")
        if parts[0] == "subprocess" and parts[-1] in _SUBPROCESS_LEAVES:
            return f"{t}()"
        if t in ("jax.block_until_ready", "jax.device_get"):
            return f"{t}() (host<->device sync)"
    if not isinstance(call.func, ast.Attribute):
        return None
    leaf = call.func.attr
    if leaf in _SOCKET_LEAVES:
        return f"socket .{leaf}()"
    if leaf == "block_until_ready":
        return ".block_until_ready() (host<->device sync)"
    recv = (_dotted(call.func.value) or "").lower()
    if leaf in ("get", "put") and \
            ("queue" in recv or recv.endswith("_q")):
        if _has_timeout_kw(call):
            return None
        if any(isinstance(a, ast.Constant) and a.value is False
               for a in call.args):
            return None                     # q.get(False) is non-blocking
        # positional timeout forms: get(block, timeout) / put(item,
        # block, timeout) are bounded waits
        if len(call.args) >= (2 if leaf == "get" else 3):
            return None
        return f"queue .{leaf}() without timeout"
    if leaf == "result" and not call.args and not _has_timeout_kw(call):
        return "Future.result() without timeout"
    return None


# ---------------------------------------------------------------------------
# the project-wide lock model
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class LockDef:
    canonical: str
    kind: str               # "threading" | "asyncio" | "unknown"
    reentrant: bool
    path: str
    line: int


class LockModel:
    """Lock inventory + class topology for one parsed Project."""

    def __init__(self, project: Project):
        self.project = project
        self.modpath_of: Dict[int, str] = {
            id(mod): mp for mp, mod in project.by_modpath.items()}
        self.locks: Dict[str, LockDef] = {}
        # modpath -> {name: LockDef}
        self.module_locks: Dict[str, Dict[str, LockDef]] = {}
        # (modpath, clsname) -> {attr: LockDef} created in that class
        self.attr_creators: Dict[Tuple[str, str], Dict[str, LockDef]] = {}
        # (modpath, clsname) -> resolved base classes
        self.class_bases: Dict[Tuple[str, str],
                               List[Tuple[str, str]]] = {}
        # id(FunctionInfo) -> (modpath, clsname)
        self.class_of_fn: Dict[int, Tuple[str, str]] = {}
        # (modpath, clsname) -> {attr: (modpath2, clsname2)} from
        # `self.attr = Class()` assignments
        self.attr_types: Dict[Tuple[str, str],
                              Dict[str, Tuple[str, str]]] = {}
        # (modpath, clsname) -> set of direct-method AST nodes
        self.method_nodes: Dict[Tuple[str, str], Set[ast.AST]] = {}
        self._classes_by_module: Dict[str, Dict[str, ast.ClassDef]] = {}
        self._ancestry_cache: Dict[Tuple[str, str],
                                   List[Tuple[str, str]]] = {}
        self._build()

    # ------------------------------------------------------------ building

    def _register(self, d: LockDef) -> LockDef:
        return self.locks.setdefault(d.canonical, d)

    def _build(self) -> None:
        proj = self.project
        for mp, mod in proj.by_modpath.items():
            self._classes_by_module[mp] = {
                c.name: c for c in mod.classes()}
        for mp, mod in proj.by_modpath.items():
            # module-level locks
            locks: Dict[str, LockDef] = {}
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call):
                    kind = _lock_ctor(node.value, mod)
                    if kind is None:
                        continue
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            locks[tgt.id] = self._register(LockDef(
                                f"{mp}.{tgt.id}", kind[0], kind[1],
                                mod.relpath, node.lineno))
            self.module_locks[mp] = locks
            # classes: bases, methods, attr locks, attr types
            for cls in mod.classes():
                key = (mp, cls.name)
                self.class_bases[key] = [
                    b for b in (self._resolve_base(e, mod, mp)
                                for e in cls.bases) if b is not None]
                methods = {n for n in cls.body
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))}
                self.method_nodes[key] = methods
                creators: Dict[str, LockDef] = {}
                types: Dict[str, Tuple[str, str]] = {}
                for m in methods:
                    for node in ast.walk(m):
                        if not (isinstance(node, ast.Assign)
                                and isinstance(node.value, ast.Call)):
                            continue
                        for tgt in node.targets:
                            if not (isinstance(tgt, ast.Attribute)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == "self"):
                                continue
                            kind = _lock_ctor(node.value, mod)
                            if kind is not None:
                                creators.setdefault(tgt.attr, self._register(
                                    LockDef(f"{mp}.{cls.name}.{tgt.attr}",
                                            kind[0], kind[1], mod.relpath,
                                            node.lineno)))
                            else:
                                ref = self._resolve_class_ref(
                                    node.value.func, mod, mp)
                                if ref is not None:
                                    types.setdefault(tgt.attr, ref)
                self.attr_creators[key] = creators
                self.attr_types[key] = types
            # map every FunctionInfo (incl. nested defs) to its class
            for fn in mod.functions:
                top = fn
                while top.parent is not None:
                    top = top.parent
                for cls in mod.classes():
                    if top.node in self.method_nodes[(mp, cls.name)]:
                        self.class_of_fn[id(fn)] = (mp, cls.name)
                        break

    def _resolve_base(self, expr: ast.AST, mod: ModuleInfo,
                      mp: str) -> Optional[Tuple[str, str]]:
        d = _dotted(expr)
        if d is None:
            return None
        parts = d.split(".")
        if len(parts) == 1:
            t = mod.from_imports.get(d)
            if t:
                bmp, _, cn = t.rpartition(".")
                if bmp in self.project.by_modpath:
                    return bmp, cn
            if d in self._classes_by_module.get(mp, {}):
                return mp, d
            return None
        full = mod.resolve_head(parts[0])
        if full and full in self.project.by_modpath and len(parts) == 2:
            return full, parts[1]
        return None

    def _resolve_class_ref(self, func: ast.AST, mod: ModuleInfo,
                           mp: str) -> Optional[Tuple[str, str]]:
        """`ThreadPool(...)` / `mod.Class(...)` -> (modpath, classname)
        when it names a project class."""
        t = _resolve_target(func, mod)
        if t is None:
            return None
        if "." not in t:
            if t in self._classes_by_module.get(mp, {}):
                return mp, t
            return None
        tmp, _, cn = t.rpartition(".")
        if tmp in self.project.by_modpath and \
                cn in self._classes_by_module.get(tmp, {}):
            return tmp, cn
        return None

    # ---------------------------------------------------------- resolution

    def ancestry(self, key: Tuple[str, str]) -> List[Tuple[str, str]]:
        """[cls, bases..., grandbases...] — pre-order, cycle-safe."""
        cached = self._ancestry_cache.get(key)
        if cached is not None:
            return cached
        out: List[Tuple[str, str]] = []
        seen: Set[Tuple[str, str]] = set()
        todo = [key]
        while todo:
            k = todo.pop(0)
            if k in seen:
                continue
            seen.add(k)
            out.append(k)
            todo.extend(self.class_bases.get(k, ()))
        self._ancestry_cache[key] = out
        return out

    def attr_lock(self, key: Tuple[str, str],
                  attr: str) -> Optional[LockDef]:
        """Resolve `self.<attr>` in class `key` to its lock, preferring
        the MOST ANCESTRAL creating class so inherited locks canonicalize
        to one id."""
        found: Optional[LockDef] = None
        for k in self.ancestry(key):
            d = self.attr_creators.get(k, {}).get(attr)
            if d is not None:
                found = d
        return found

    def resolve_lock_expr(self, fn: FunctionInfo,
                          expr: ast.AST) -> Optional[LockDef]:
        """Resolve a `with`-statement context expression to a lock."""
        d = _dotted(expr)
        if d is None:
            return None
        mod = fn.module
        mp = self.modpath_of.get(id(mod))
        if mp is None:
            return None
        parts = d.split(".")
        if parts[0] == "self" and len(parts) == 2:
            key = self.class_of_fn.get(id(fn))
            if key is not None:
                found = self.attr_lock(key, parts[1])
                if found is not None:
                    return found
                if any(h in parts[1].lower() for h in _LOCK_NAME_HINTS):
                    # unseen creation (built dynamically, or passed in):
                    # still track the order, anchored on the using class
                    return self._register(LockDef(
                        f"{mp}.{key[1]}.{parts[1]}", "unknown", True,
                        mod.relpath, getattr(expr, "lineno", 1)))
            return None
        if len(parts) == 1:
            return self.module_locks.get(mp, {}).get(parts[0])
        if len(parts) == 2:
            full = mod.resolve_head(parts[0])
            if full and full in self.project.by_modpath:
                return self.module_locks.get(full, {}).get(parts[1])
        return None

    def resolve_calls(self, call: ast.Call,
                      fn: FunctionInfo) -> List[FunctionInfo]:
        """Callees of `call` that execute SYNCHRONOUSLY in the caller —
        direct names, `self.m()`, module-alias calls, and
        `self.<attr>.<method>()` through attr_types.  Callables passed as
        ARGUMENTS are excluded on purpose (spawn targets run later)."""
        f = call.func
        mod = fn.module
        if isinstance(f, ast.Name):
            return self.project._resolve_call(mod, f.id, None)
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name):
                return self.project._resolve_call(mod, f.attr, f.value.id)
            if isinstance(f.value, ast.Attribute) and \
                    isinstance(f.value.value, ast.Name) and \
                    f.value.value.id == "self":
                key = self.class_of_fn.get(id(fn))
                if key is not None:
                    ref = None
                    for k in self.ancestry(key):
                        ref = self.attr_types.get(k, {}).get(f.value.attr)
                        if ref is not None:
                            break
                    if ref is not None:
                        tmod = self.project.by_modpath.get(ref[0])
                        nodes = self.method_nodes.get(ref, set())
                        if tmod is not None:
                            return [g for g in tmod.functions_named(f.attr)
                                    if g.node in nodes]
        return []


# ---------------------------------------------------------------------------
# per-function scan
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _FnScan:
    fn: FunctionInfo
    acquires: Set[str] = dataclasses.field(default_factory=set)
    #: (held, acquired, line) from syntactic nesting
    edges: List[Tuple[str, str, int]] = dataclasses.field(
        default_factory=list)
    #: (held_tuple, call_node, line) for calls under at least one lock
    locked_calls: List[Tuple[Tuple[str, ...], ast.Call, int]] = \
        dataclasses.field(default_factory=list)
    #: (held_tuple, desc, line) direct blocking ops under a lock
    locked_blocking: List[Tuple[Tuple[str, ...], str, int]] = \
        dataclasses.field(default_factory=list)
    #: blocking ops anywhere in the function (for caller-side reporting)
    blocking: Dict[str, int] = dataclasses.field(default_factory=dict)
    #: resolved synchronous callees (whole function)
    callees: List[FunctionInfo] = dataclasses.field(default_factory=list)
    #: non-reentrant lock re-acquired under itself: (canonical, line)
    self_deadlocks: List[Tuple[str, int]] = dataclasses.field(
        default_factory=list)


def _scan_function(fn: FunctionInfo, model: LockModel) -> _FnScan:
    scan = _FnScan(fn)
    nested = {f.node for f in fn.module.functions if f.parent is fn}

    def visit(node: ast.AST, held: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if child in nested:
                continue
            now = held
            if isinstance(child, (ast.With, ast.AsyncWith)):
                acquired: List[str] = []
                for item in child.items:
                    lock = model.resolve_lock_expr(fn, item.context_expr)
                    if lock is None:
                        continue
                    c = lock.canonical
                    # items of one `with A, B:` enter sequentially — B is
                    # acquired with A already held, exactly like nesting
                    cur = held + acquired
                    if c in cur:
                        if lock.kind == "threading" and not lock.reentrant:
                            scan.self_deadlocks.append((c, child.lineno))
                    else:
                        for h in cur:
                            scan.edges.append((h, c, child.lineno))
                        acquired.append(c)
                    scan.acquires.add(c)
                if acquired:
                    now = held + acquired
            if isinstance(child, ast.Call):
                callees = model.resolve_calls(child, fn)
                scan.callees.extend(callees)
                desc = _blocking_desc(child, fn.module)
                if desc is not None:
                    scan.blocking.setdefault(desc, child.lineno)
                if now:
                    if callees:
                        scan.locked_calls.append(
                            (tuple(dict.fromkeys(now)), child,
                             child.lineno))
                    if desc is not None:
                        scan.locked_blocking.append(
                            (tuple(dict.fromkeys(now)), desc,
                             child.lineno))
            visit(child, now)

    visit(fn.node, [])
    return scan


# ---------------------------------------------------------------------------
# interprocedural fixpoint + graph assembly
# ---------------------------------------------------------------------------

def get_model(project: Project) -> "LockModel":
    """Memoized LockModel for a Project — lockgraph and asyncrules both
    run per lint invocation, and class-topology + attr-type inference
    over every module is the expensive part; build it once."""
    model = project.cache.get("lockgraph.model")
    if model is None or model.project is not project:
        model = LockModel(project)
        project.cache["lockgraph.model"] = model
    return model


@dataclasses.dataclass
class _Analysis:
    model: LockModel
    scans: Dict[int, _FnScan]
    reach_acq: Dict[int, Set[str]]
    reach_blk: Dict[int, Dict[str, str]]
    edges: Dict[str, Set[str]]
    witness: Dict[Tuple[str, str], Tuple[str, int, str, str]]


def _analyze(project: Project) -> _Analysis:
    model = get_model(project)
    scans = {id(fn): _scan_function(fn, model)
             for mod in project.modules.values() for fn in mod.functions}

    # fixpoint: locks (and blocking ops) reachable through synchronous
    # calls from each function
    reach_acq: Dict[int, Set[str]] = {
        k: set(s.acquires) for k, s in scans.items()}
    reach_blk: Dict[int, Dict[str, str]] = {
        k: {d: s.fn.qualname for d in s.blocking}
        for k, s in scans.items()}
    changed = True
    while changed:
        changed = False
        for k, s in scans.items():
            for callee in s.callees:
                ck = id(callee)
                if ck not in scans or ck == k:
                    continue
                before = len(reach_acq[k])
                reach_acq[k] |= reach_acq[ck]
                if len(reach_acq[k]) != before:
                    changed = True
                for desc, origin in reach_blk[ck].items():
                    if desc not in reach_blk[k]:
                        reach_blk[k][desc] = origin
                        changed = True

    edges: Dict[str, Set[str]] = {}
    witness: Dict[Tuple[str, str], Tuple[str, int, str, str]] = {}

    def add_edge(a: str, b: str, path: str, line: int, symbol: str,
                 note: str) -> None:
        if a == b:
            return
        edges.setdefault(a, set()).add(b)
        witness.setdefault((a, b), (path, line, symbol, note))

    for s in scans.values():
        relpath = s.fn.module.relpath
        for a, b, line in s.edges:
            add_edge(a, b, relpath, line, s.fn.qualname, "nested `with`")
        for held, call, line in s.locked_calls:
            for callee in model.resolve_calls(call, s.fn):
                ck = id(callee)
                if ck not in scans:
                    continue
                for b in reach_acq[ck]:
                    for a in held:
                        add_edge(a, b, relpath, line, s.fn.qualname,
                                 f"via call to `{callee.qualname}`")
    return _Analysis(model, scans, reach_acq, reach_blk, edges, witness)


def build_order_graph(project: Project
                      ) -> Tuple[LockModel,
                                 Dict[str, Set[str]],
                                 Dict[Tuple[str, str],
                                      Tuple[str, int, str, str]]]:
    """-> (model, edges {A: {B}}, witness {(A,B): (path, line, symbol,
    note)}).  Public so tests can cross-check the static graph against
    locksan's runtime-observed one."""
    a = _analyze(project)
    return a.model, a.edges, a.witness


def _sccs(edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan strongly-connected components over the edge map."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strong(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on.add(v)
        for w in sorted(edges.get(v, ())):
            if w not in index:
                strong(w)
                low[v] = min(low[v], low[w])
            elif w in on:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            comp = []
            while True:
                w = stack.pop()
                on.discard(w)
                comp.append(w)
                if w == v:
                    break
            out.append(comp)

    nodes = set(edges)
    for vs in edges.values():
        nodes |= vs
    for v in sorted(nodes):
        if v not in index:
            strong(v)
    return [c for c in out if len(c) > 1]


def _cycle_in(component: List[str],
              edges: Dict[str, Set[str]]) -> List[str]:
    """One concrete cycle inside an SCC (guaranteed to exist)."""
    comp = set(component)
    start = sorted(component)[0]
    path = [start]
    seen = {start}
    node = start
    while True:
        nxt = sorted(n for n in edges.get(node, ()) if n in comp)[0]
        if nxt in seen:
            return path[path.index(nxt):] + [nxt]
        path.append(nxt)
        seen.add(nxt)
        node = nxt


def check(project: Project) -> List[Finding]:
    ana = _analyze(project)
    model, scans = ana.model, ana.scans
    edges, witness = ana.edges, ana.witness
    out: List[Finding] = []

    # ---- GL701: cycles + non-reentrant self-acquisition -------------------
    for comp in _sccs(edges):
        cycle = _cycle_in(comp, edges)
        steps = []
        for a, b in zip(cycle, cycle[1:]):
            path, line, symbol, note = witness[(a, b)]
            steps.append(f"{a} -> {b} ({symbol} at {path}:{line}, {note})")
        path0, line0, symbol0, _ = witness[(cycle[0], cycle[1])]
        out.append(Finding(
            "GL701", path0, line0,
            "lock-order cycle (potential deadlock): " + "; ".join(steps),
            symbol0))
    for s in scans.values():
        for canonical, line in s.self_deadlocks:
            out.append(Finding(
                "GL701", s.fn.module.relpath, line,
                f"non-reentrant lock `{canonical}` re-acquired while "
                "already held — guaranteed self-deadlock (use an RLock "
                "or restructure)", s.fn.qualname))
    # interprocedural form: caller holds a non-reentrant lock and a
    # synchronous callee re-acquires it (add_edge drops a==b edges, so
    # this is checked separately)
    self_seen: Set[Tuple[str, str]] = set()
    for s in scans.values():
        for held, call, line in s.locked_calls:
            for callee in model.resolve_calls(call, s.fn):
                ck = id(callee)
                if ck not in scans:
                    continue
                for b in ana.reach_acq[ck]:
                    if b not in held:
                        continue
                    lock = model.locks.get(b)
                    if lock is None or lock.kind != "threading" or \
                            lock.reentrant:
                        continue
                    key = (s.fn.qualname, b)
                    if key in self_seen:
                        continue
                    self_seen.add(key)
                    out.append(Finding(
                        "GL701", s.fn.module.relpath, line,
                        f"non-reentrant lock `{b}` re-acquired through "
                        f"call to `{callee.qualname}` while already held "
                        "— guaranteed self-deadlock on the same "
                        "instance", s.fn.qualname))

    # ---- GL702: blocking under a held lock ---------------------------------
    reach_blk = ana.reach_blk
    reported: Set[Tuple[str, str, str]] = set()
    for s in scans.values():
        relpath = s.fn.module.relpath
        for held, desc, line in s.locked_blocking:
            for a in held:
                key = (s.fn.qualname, a, desc)
                if key in reported:
                    continue
                reported.add(key)
                out.append(Finding(
                    "GL702", relpath, line,
                    f"{desc} while holding `{a}` — every thread behind "
                    "the lock stalls for the full wait", s.fn.qualname))
        for held, call, line in s.locked_calls:
            for callee in model.resolve_calls(call, s.fn):
                ck = id(callee)
                if ck not in scans:
                    continue
                for desc, origin in reach_blk[ck].items():
                    for a in held:
                        key = (s.fn.qualname, a, desc)
                        if key in reported:
                            continue
                        reported.add(key)
                        out.append(Finding(
                            "GL702", relpath, line,
                            f"call reaches {desc} (in `{origin}`) while "
                            f"holding `{a}` — every thread behind the "
                            "lock stalls for the full wait",
                            s.fn.qualname))

    # ---- GL704: leaked thread/task handles ---------------------------------
    for mod in project.modules.values():
        out.extend(_check_leaks(mod, model))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))


# ---------------------------------------------------------------------------
# GL704 — thread/task leak detection
# ---------------------------------------------------------------------------

def _handle_kind(call: ast.Call, mod: ModuleInfo) -> Optional[str]:
    t = _resolve_target(call.func, mod)
    if t == "threading.Thread":
        return "thread"
    leaf = (t or "").split(".")[-1] if t else (
        call.func.attr if isinstance(call.func, ast.Attribute) else "")
    if leaf in ("create_task", "ensure_future"):
        return "task"
    return None


def _shutdown_surface(mod: ModuleInfo
                      ) -> Tuple[Set[str], Set[str], bool]:
    """(attrs with .join/.cancel, local names with .join/.cancel,
    any_loop_join) for the module."""
    attr_joined: Set[str] = set()
    name_joined: Set[str] = set()
    any_loop_join = False
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("join", "cancel"):
            target = node.func.value
            if isinstance(target, ast.Attribute):
                attr_joined.add(target.attr)
            elif isinstance(target, ast.Name):
                name_joined.add(target.id)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            targets = {n.id for n in ast.walk(node.target)
                       if isinstance(n, ast.Name)}
            for inner in ast.walk(node):
                if isinstance(inner, ast.Call) and \
                        isinstance(inner.func, ast.Attribute) and \
                        inner.func.attr in ("join", "cancel") and \
                        isinstance(inner.func.value, ast.Name) and \
                        inner.func.value.id in targets:
                    any_loop_join = True
    return attr_joined, name_joined, any_loop_join


def _enclosing_fn(mod: ModuleInfo, node: ast.AST) -> Optional[FunctionInfo]:
    best: Optional[FunctionInfo] = None
    line = getattr(node, "lineno", None)
    if line is None:
        return None
    for fn in mod.functions:
        end = getattr(fn.node, "end_lineno", fn.node.lineno)
        if fn.node.lineno <= line <= end and \
                (best is None or fn.node.lineno > best.node.lineno):
            best = fn
    return best


def _check_leaks(mod: ModuleInfo, model: LockModel) -> List[Finding]:
    out: List[Finding] = []
    attr_joined, name_joined, any_loop_join = _shutdown_surface(mod)

    def attr_ok(attr: str) -> bool:
        return attr in attr_joined

    # map: statement handling.  Walk Assign / bare-Expr statements.
    for node in ast.walk(mod.tree):
        ctor: Optional[ast.Call] = None
        kind: Optional[str] = None
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Call):
            kind = _handle_kind(node.value, mod)
            ctor = node.value
            if kind is None:
                continue
            tgt = node.targets[0]
            if isinstance(tgt, ast.Attribute):
                if not attr_ok(tgt.attr):
                    out.append(_leak_finding(mod, ctor, kind, tgt.attr))
            elif isinstance(tgt, ast.Name):
                if not _local_handle_ok(mod, node, tgt.id, attr_joined,
                                        name_joined, any_loop_join):
                    out.append(_leak_finding(mod, ctor, kind, tgt.id))
        elif isinstance(node, ast.Expr) and \
                isinstance(node.value, ast.Call):
            call = node.value
            kind = _handle_kind(call, mod)
            if kind is None and isinstance(call.func, ast.Attribute) and \
                    isinstance(call.func.value, ast.Call):
                # Thread(...).start() — the handle is dropped on the spot
                kind = _handle_kind(call.func.value, mod)
                call = call.func.value
            if kind is not None:
                # a dropped task can be GC'd mid-flight and can never be
                # cancelled on shutdown; a dropped thread can never be
                # joined
                out.append(_leak_finding(mod, call, kind, None))
    return out


def _local_handle_ok(mod: ModuleInfo, assign: ast.Assign, name: str,
                     attr_joined: Set[str], name_joined: Set[str],
                     any_loop_join: bool) -> bool:
    fn = _enclosing_fn(mod, assign)
    scope = fn.node if fn is not None else mod.tree
    if name in name_joined:
        return True
    for node in ast.walk(scope):
        # self.Y = t  -> judged as attribute Y
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == name:
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and \
                        (tgt.attr in attr_joined or any_loop_join):
                    return True
        # X.append(t) -> worker-collection idiom; accepted when the
        # module joins/cancels loop targets anywhere
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("append", "add") and \
                any(isinstance(a, ast.Name) and a.id == name
                    for a in node.args):
            if any_loop_join:
                return True
            tv = node.func.value
            if isinstance(tv, ast.Attribute) and tv.attr in attr_joined:
                return True
        # return t -> the caller owns the handle now
        if isinstance(node, ast.Return) and node.value is not None:
            for n in ast.walk(node.value):
                if isinstance(n, ast.Name) and n.id == name:
                    return True
    return False


def _leak_finding(mod: ModuleInfo, ctor: ast.Call, kind: str,
                  handle: Optional[str]) -> Finding:
    fn = _enclosing_fn(mod, ctor)
    what = "Thread" if kind == "thread" else "task"
    where = f"`{handle}`" if handle else "an unnamed handle"
    return Finding(
        "GL704", mod.relpath, ctor.lineno,
        f"{what} handle {where} never reaches a join()/cancel() on any "
        "shutdown path in this module — the "
        f"{'thread outlives' if kind == 'thread' else 'task can be GC-collected mid-flight and outlives'} "
        "its owner silently",
        fn.qualname if fn is not None else "")
