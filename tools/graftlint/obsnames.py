"""GL6xx — observability-name lint (metric-cardinality bound).

The telemetry registry (utils/metrics.py) keys series directly off their
names and never expires one: a span/counter/histogram name interpolated
from runtime values (an f-string, concatenation, %-format, .format, a
per-call variable) mints a fresh series per distinct value — unbounded
registry growth in a long-lived server, and every Prometheus scrape
re-serializes all of it.  Names must therefore be STRING LITERALS at the
call site; module-level `NAME = "..."` constants are accepted too (their
value set is bounded by definition).

Rules:

* GL601 — the name argument of `trace.span(...)` / `trace.record(...)`
  is not a string literal or module-level string constant.
* GL602 — the name argument of a metrics-registry call
  (`metrics.counter/gauge/histogram/inc/set_gauge/observe/
  counter_value/histogram_or_none`) is not a string literal or
  module-level string constant.
* GL603 — the `kind` argument of a flight-recorder call
  (`flightrec.record(tier, kind, ...)` / `flightrec.span(tier, kind,
  ...)`) is not a string literal or module-level string constant: the
  Chrome-trace export keys tracks off the kind and the ring never
  expires a name, so kinds are a bounded taxonomy by the same
  cardinality argument as GL601/602.
* GL606 — the name argument of a quality-monitor series call
  (`qualmon.gauge(name, ...)` / `qualmon.inc(name, ...)`) is not a
  string literal or module-level string constant: the labeled quality
  exposition keys series off the name and the windows never expire
  one.  The `mode`/`shard` LABELS are out of scope — they are bounded
  by deployment (search modes are an enum, shards come from the
  service config), exactly like flightrec's tier argument.
* GL607 — the stage argument of a host-profiler pin
  (`hostprof.set_stage(stage, ...)` / `hostprof.stage(stage, ...)`) is
  not a string literal or module-level string constant: the folded-
  stack aggregate injects a synthetic ``stage:<name>`` frame per
  sample and the per-stage counters never expire a name, so stages
  are a bounded taxonomy (decode/queue/execute/encode/merge) by the
  same cardinality argument.  The `rid` argument is out of scope —
  rid attribution is a bounded LRU by design.
* GL608 — the name argument of a timeline series record
  (`timeline.record(name, value, ...)`) is not a string literal or
  module-level string constant: the time-series store keys fixed-size
  rings off the name and never expires one, so the series taxonomy
  (timeline/SLO/canary series alike — the SLO engine and canary
  prober both publish through this call) must be bounded.  The
  `label` argument is out of scope — labels are deployment-bounded
  (index names, objective names), the qualmon shard-label rationale.
* GL609 — the rule argument of a controller decision-audit record
  (`ctlaudit.record(rule, ...)`) is not a string literal or
  module-level string constant: the audit ring is the control plane's
  accountability surface — dashboards and the acceptance drill key off
  rule names, the ring counts decisions per rule, and a dynamic rule
  name would make the decision taxonomy (burn_step_down /
  revert_on_worse / canary_floor_veto / ...) unsearchable.  The `knob`
  argument is out of scope — knob names come from the core/params
  live-actuation registry, bounded by deployment like flightrec's
  tier.

Calls are resolved through import aliases (`from sptag_tpu.utils import
trace` / `import sptag_tpu.utils.metrics as metrics` / from-imports of the
functions themselves), so the modules' own internal plumbing that passes a
`name` PARAMETER through is out of scope by construction — the lint
surface is the call sites that choose the name.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.graftlint.core import Finding, ModuleInfo, Project, _dotted

RULES = {
    "GL601": "trace span/record name is not a string literal — dynamic "
             "names make metric cardinality unbounded",
    "GL602": "metrics registry name is not a string literal — dynamic "
             "names make metric cardinality unbounded",
    "GL603": "flight-recorder event kind is not a string literal — "
             "dynamic kinds make the event taxonomy unbounded",
    "GL606": "quality-monitor series name is not a string literal — "
             "dynamic names make the quality exposition unbounded",
    "GL607": "host-profiler stage name is not a string literal — "
             "dynamic stages make the folded-stack taxonomy unbounded",
    "GL608": "timeline series name is not a string literal — dynamic "
             "names make the time-series store unbounded",
    "GL609": "controller audit rule name is not a string literal — "
             "dynamic rule names make the decision taxonomy unbounded",
}

_TRACE_MODULE = "sptag_tpu.utils.trace"
_METRICS_MODULE = "sptag_tpu.utils.metrics"
_FLIGHT_MODULE = "sptag_tpu.utils.flightrec"
_QUALMON_MODULE = "sptag_tpu.utils.qualmon"
_HOSTPROF_MODULE = "sptag_tpu.utils.hostprof"
_TIMELINE_MODULE = "sptag_tpu.utils.timeline"
_CTLAUDIT_MODULE = "sptag_tpu.serve.ctlaudit"

_TRACE_FNS = {"span", "record"}
_METRICS_FNS = {"counter", "gauge", "histogram", "inc", "set_gauge",
                "observe", "counter_value", "histogram_or_none"}
_FLIGHT_FNS = {"record", "span"}
_QUALMON_FNS = {"gauge", "inc"}
_HOSTPROF_FNS = {"set_stage", "stage"}
_TIMELINE_FNS = {"record"}
_CTLAUDIT_FNS = {"record"}

#: per-rule (positional index, keyword name) of the argument that must
#: be a bounded string — GL60x's lint surface
_NAME_ARG = {"GL601": (0, "name"), "GL602": (0, "name"),
             "GL603": (1, "kind"), "GL606": (0, "name"),
             "GL607": (0, "stage"), "GL608": (0, "name"),
             "GL609": (0, "rule")}


def _module_str_constants(mod: ModuleInfo) -> Set[str]:
    """Names bound at module level to a string constant (e.g.
    `TRACE_SPAN = "xla.backend_compile"`) — bounded by definition."""
    out: Set[str] = set()
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
    return out


def _rule_for_call(call: ast.Call, mod: ModuleInfo) -> Optional[str]:
    """GL601/GL602 when this call targets the trace/metrics registries
    (resolved through the module's import aliases), else None."""
    func = call.func
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        full = mod.resolve_head(func.value.id)
        if full == _TRACE_MODULE and func.attr in _TRACE_FNS:
            return "GL601"
        if full == _METRICS_MODULE and func.attr in _METRICS_FNS:
            return "GL602"
        if full == _FLIGHT_MODULE and func.attr in _FLIGHT_FNS:
            return "GL603"
        if full == _QUALMON_MODULE and func.attr in _QUALMON_FNS:
            return "GL606"
        if full == _HOSTPROF_MODULE and func.attr in _HOSTPROF_FNS:
            return "GL607"
        if full == _TIMELINE_MODULE and func.attr in _TIMELINE_FNS:
            return "GL608"
        if full == _CTLAUDIT_MODULE and func.attr in _CTLAUDIT_FNS:
            return "GL609"
        return None
    if isinstance(func, ast.Name):
        target = mod.from_imports.get(func.id, "")
        modpath, _, sym = target.rpartition(".")
        if modpath == _TRACE_MODULE and sym in _TRACE_FNS:
            return "GL601"
        if modpath == _METRICS_MODULE and sym in _METRICS_FNS:
            return "GL602"
        if modpath == _FLIGHT_MODULE and sym in _FLIGHT_FNS:
            return "GL603"
        if modpath == _QUALMON_MODULE and sym in _QUALMON_FNS:
            return "GL606"
        if modpath == _HOSTPROF_MODULE and sym in _HOSTPROF_FNS:
            return "GL607"
        if modpath == _TIMELINE_MODULE and sym in _TIMELINE_FNS:
            return "GL608"
        if modpath == _CTLAUDIT_MODULE and sym in _CTLAUDIT_FNS:
            return "GL609"
    return None


def _name_arg(call: ast.Call, rule: str) -> Optional[ast.AST]:
    pos, kwname = _NAME_ARG[rule]
    if len(call.args) > pos:
        return call.args[pos]
    for kw in call.keywords:
        if kw.arg == kwname:
            return kw.value
    return None


def _is_bounded(arg: ast.AST, constants: Set[str]) -> bool:
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return True
    return isinstance(arg, ast.Name) and arg.id in constants


def _describe(arg: ast.AST) -> str:
    if isinstance(arg, ast.JoinedStr):
        return "an f-string"
    if isinstance(arg, ast.BinOp):
        return "a concatenation/format expression"
    if isinstance(arg, ast.Call):
        return "a call result"
    if isinstance(arg, ast.Name):
        return f"the variable `{arg.id}`"
    return "a dynamic expression"


def _check_module(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    constants = _module_str_constants(mod)

    def enclosing(lineno: int) -> str:
        best, best_line = "", -1
        for fn in mod.functions:
            end = getattr(fn.node, "end_lineno", fn.node.lineno)
            if fn.node.lineno <= lineno <= end and \
                    fn.node.lineno > best_line:
                best, best_line = fn.qualname, fn.node.lineno
        return best

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        rule = _rule_for_call(node, mod)
        if rule is None:
            continue
        arg = _name_arg(node, rule)
        if arg is None or _is_bounded(arg, constants):
            continue
        fn_name = _dotted(node.func) or "<call>"
        what = ("kind" if rule == "GL603"
                else "stage" if rule == "GL607"
                else "rule" if rule == "GL609" else "name")
        out.append(Finding(
            rule, mod.relpath, node.lineno,
            f"`{fn_name}` {what} is {_describe(arg)} — use a string "
            "literal (or a module-level str constant) so metric "
            "cardinality stays bounded", enclosing(node.lineno)))
    return out


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules.values():
        out.extend(_check_module(mod))
    return out
