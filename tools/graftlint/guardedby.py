"""GL80x — static guarded-by inference (lockset race detection).

PR 3's GL7xx pass proves locks are acquired in a safe ORDER; nothing
checked that shared state is accessed under any lock at all.  PRs 9 and
11 made that the scariest surface in the codebase: epoch-swapped engines
and schedulers, delta-shard tails, WAL handles and mesh placements are
mutated by background refine/swap threads while reader threads pin them
lock-free.  This checker infers, per class attribute (and per module
global), WHICH lock guards it — from the locks actually held at its
write sites — and then reports writes that break the inferred contract.

The pass reuses lockgraph's project-wide LockModel (lock inventory
canonicalized through class ancestry, `self.<attr> = Class()` attr
types, call resolution) and adds:

* a THREAD-ENTRY set: every callable handed to ``threading.Thread(
  target=)`` / ``Timer``, a ``ThreadPool.add``/``submit`` job,
  ``run_in_executor``, ``asyncio.create_task``/``ensure_future``/
  ``call_soon*`` or an ``asyncio.start_server`` handler — plus
  everything reachable from those through the call graph.  An attribute
  is SHARED when a thread-reachable function touches it; attributes only
  the constructing thread sees are never reported;
* an interprocedural HELD-ON-ENTRY fixpoint: a helper called only while
  ``self._lock`` is held counts its writes as guarded (must-hold:
  intersection over all call sites; a thread entry point holds nothing).

Rules:

* GL801 — unguarded write to a shared attribute: a guard exists (the
  intersection of locks held at the attribute's locked write sites is
  non-empty) but THIS write holds it on no interprocedural path.
* GL802 — unguarded read-modify-write of a shared attribute: ``x += 1``,
  ``self.d[k] = v``, ``self.seen.add(k)`` and friends with no lock held
  — lost updates even when every individual write is atomic in CPython.
* GL803 — inconsistent guards: the attribute's locked write sites hold
  DISJOINT locks (two writers each think their lock protects it).
* GL804 — epoch-pin violation: a swappable attribute (re-published at
  runtime by a background thread, e.g. ``self._engine``/``self._impl``)
  is re-read lock-free more than once in a single call instead of being
  pinned to a local — the reader can observe two different epochs
  mid-call, the exact bug class PR 9's ``_get_engine`` fix closed.
* GL805 — escaping before publish: ``self`` (or a bound method) is
  handed to a thread/task/callback inside ``__init__`` while later
  statements still assign attributes — the spawned code can observe a
  partially-built object.
* GL806 — a plain ``threading.Lock()``/``RLock()``/argless
  ``Condition()`` in sptag_tpu code: invisible to locksan's order
  sanitizer, contention ledger AND race sanitizer — use
  ``locksan.make_lock(name)``.  (``Condition(self._lock)`` wrapping a
  named lock is fine and is canonicalized to the wrapped lock.)

The runtime complement is the Eraser-style race sanitizer in
sptag_tpu/utils/locksan.py (``SPTAG_RACESAN=1``); tests/test_racesan.py
cross-checks this module's ``infer_guards()`` against the locksets a
live mutate-under-load workload actually held.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from tools.graftlint.core import (
    Finding,
    FunctionInfo,
    ModuleInfo,
    Project,
    _dotted,
)
from tools.graftlint.lockgraph import (
    LockModel,
    _resolve_target,
    get_model,
)

RULES = {
    "GL801": "unguarded write to a shared attribute whose inferred "
             "guard is held at its other write sites",
    "GL802": "unguarded read-modify-write of a shared attribute "
             "(compound update with no lock held)",
    "GL803": "inconsistent guards: attribute written under two "
             "disjoint locks",
    "GL804": "swappable attribute re-read mid-call instead of pinned "
             "to a local (epoch-pin violation)",
    "GL805": "self escapes to a thread/task/callback before __init__ "
             "completes",
    "GL806": "plain threading lock invisible to the locksan runtime "
             "(use locksan.make_lock)",
}

#: call leaves that hand a callable to ANOTHER OS THREAD — writes
#: reachable from these can race with everything
_THREAD_LEAVES = {"Thread", "Timer", "add", "submit", "apply_async",
                  "run_in_executor"}
#: call leaves that schedule a callable on an asyncio EVENT LOOP — one
#: logical thread: coroutines interleave only at `await`, so their
#: writes race with thread-side writes but not with each other (the
#: cross-await hazards are GL7xx/asyncrules territory)
_ASYNC_LEAVES = {"create_task", "ensure_future", "call_soon",
                 "call_soon_threadsafe", "call_later", "start_server"}
_SPAWN_LEAVES = _THREAD_LEAVES | _ASYNC_LEAVES
#: keyword names that carry the callable at those call sites
_SPAWN_KWARGS = ("target", "func", "fn", "callback", "job")

#: method leaves that mutate their receiver in place
_MUTATOR_LEAVES = {"append", "appendleft", "extend", "extendleft",
                   "insert", "add", "update", "setdefault", "pop",
                   "popitem", "remove", "discard", "clear"}

#: attributes everyone may write lock-free: per-instance constants
#: assigned once.  (Heuristic escape hatch is the baseline, not this.)
_INIT_ONLY = "__init__"


# ---------------------------------------------------------------------------
# per-function scan
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _Write:
    attr: str
    line: int
    fn: FunctionInfo
    held: FrozenSet[str]          # syntactic only; H(fn) added later
    compound: bool                # RMW / container mutation
    is_init: bool


@dataclasses.dataclass
class _Scan:
    fn: FunctionInfo
    writes: List[_Write] = dataclasses.field(default_factory=list)
    #: attr -> [(line, held)]
    reads: Dict[str, List[Tuple[int, FrozenSet[str]]]] = \
        dataclasses.field(default_factory=dict)
    #: (call_node, held, line)
    calls: List[Tuple[ast.Call, FrozenSet[str], int]] = \
        dataclasses.field(default_factory=list)
    #: module-global writes: (name, line, held, compound)
    gwrites: List[Tuple[str, int, FrozenSet[str], bool]] = \
        dataclasses.field(default_factory=list)


class _Pass:
    def __init__(self, project: Project):
        self.project = project
        self.model: LockModel = get_model(project)
        #: class key -> {cond_attr: wrapped_lock_attr} from
        #: `self.A = threading.Condition(self.B)`
        self.cond_alias: Dict[Tuple[str, str], Dict[str, str]] = {}
        #: modpath -> {cond_name: wrapped_lock_name}
        self.mod_cond_alias: Dict[str, Dict[str, str]] = {}
        self.scans: Dict[int, _Scan] = {}
        self.entries: Set[int] = set()          # thread + async entries
        self.thread_entries: Set[int] = set()
        self.reachable: Set[int] = set()        # thread-reachable
        self.async_reachable: Set[int] = set()
        self.held_entry: Dict[int, Optional[Set[str]]] = {}
        #: class key -> direct subclasses (reverse of class_bases)
        self.subclasses: Dict[Tuple[str, str],
                              List[Tuple[str, str]]] = {}
        for key, bases in self.model.class_bases.items():
            for b in bases:
                self.subclasses.setdefault(b, []).append(key)
        self._build_aliases()
        self._scan_all()
        self._find_entries()
        self._fixpoint_held_entry()

    # ------------------------------------------------------------ aliases

    def _build_aliases(self) -> None:
        for mp, mod in self.project.by_modpath.items():
            maliases: Dict[str, str] = {}
            for node in mod.tree.body:
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and node.value.args:
                    if _resolve_target(node.value.func, mod) == \
                            "threading.Condition":
                        src = _dotted(node.value.args[0])
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name) and src:
                                maliases[tgt.id] = src
            self.mod_cond_alias[mp] = maliases
            for key, nodes in self.model.method_nodes.items():
                if key[0] != mp:
                    continue
                aliases: Dict[str, str] = {}
                for m in nodes:
                    for node in ast.walk(m):
                        if not (isinstance(node, ast.Assign)
                                and isinstance(node.value, ast.Call)
                                and node.value.args):
                            continue
                        if _resolve_target(node.value.func, mod) != \
                                "threading.Condition":
                            continue
                        src = _dotted(node.value.args[0])
                        if not (src and src.startswith("self.")):
                            continue
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Attribute) and \
                                    isinstance(tgt.value, ast.Name) and \
                                    tgt.value.id == "self":
                                aliases[tgt.attr] = src.split(".", 1)[1]
                self.cond_alias[key] = aliases

    def _held_name(self, fn: FunctionInfo, expr: ast.AST) -> Optional[str]:
        """Canonical lock id for a `with` context expr, resolving
        Condition wrappers to the lock they wrap."""
        d = _dotted(expr)
        if d is not None:
            key = self.model.class_of_fn.get(id(fn))
            parts = d.split(".")
            if parts[0] == "self" and len(parts) == 2 and key is not None:
                alias = self.cond_alias.get(key, {}).get(parts[1])
                if alias:
                    expr = ast.Attribute(
                        value=ast.Name(id="self", ctx=ast.Load()),
                        attr=alias, ctx=ast.Load())
                    ast.copy_location(expr, ast.Name(id="self"))
            elif len(parts) == 1:
                mp = self.model.modpath_of.get(id(fn.module))
                alias = self.mod_cond_alias.get(mp or "", {}).get(parts[0])
                if alias:
                    expr = ast.Name(id=alias, ctx=ast.Load())
        lock = self.model.resolve_lock_expr(fn, expr)
        return lock.canonical if lock is not None else None

    # --------------------------------------------------------------- scan

    def _scan_all(self) -> None:
        for mod in self.project.modules.values():
            for fn in mod.functions:
                self.scans[id(fn)] = self._scan_fn(fn)

    def _scan_fn(self, fn: FunctionInfo) -> _Scan:
        scan = _Scan(fn)
        nested = {f.node for f in fn.module.functions if f.parent is fn}
        is_init = fn.name == _INIT_ONLY
        gnames: Set[str] = set()
        for node in ast.walk(fn.node):
            if isinstance(node, ast.Global):
                gnames.update(node.names)

        def self_attr(node: ast.AST) -> Optional[str]:
            if isinstance(node, ast.Attribute) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id == "self":
                return node.attr
            return None

        def note_write(attr: str, line: int, held: List[str],
                       compound: bool) -> None:
            scan.writes.append(_Write(attr, line, fn,
                                      frozenset(held), compound, is_init))

        def visit(node: ast.AST, held: List[str]) -> None:
            for child in ast.iter_child_nodes(node):
                if child in nested:
                    continue
                now = held
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    acquired: List[str] = []
                    for item in child.items:
                        c = self._held_name(fn, item.context_expr)
                        if c is not None and c not in held + acquired:
                            acquired.append(c)
                    if acquired:
                        now = held + acquired
                # ---- writes -------------------------------------------
                if isinstance(child, ast.Assign):
                    # `self.x = f(self.x)` is a check-then-set RMW, not
                    # an atomic publish
                    rhs_reads = {self_attr(n)
                                 for n in ast.walk(child.value)
                                 if isinstance(n, ast.Attribute)
                                 and isinstance(n.ctx, ast.Load)}
                    for tgt in child.targets:
                        tgts = tgt.elts if isinstance(
                            tgt, (ast.Tuple, ast.List)) else [tgt]
                        for t in tgts:
                            a = self_attr(t)
                            if a is not None:
                                note_write(a, child.lineno, now,
                                           a in rhs_reads)
                            elif isinstance(t, ast.Subscript):
                                a = self_attr(t.value)
                                if a is not None:
                                    note_write(a, child.lineno, now, True)
                            elif isinstance(t, ast.Name) and \
                                    t.id in gnames:
                                scan.gwrites.append(
                                    (t.id, child.lineno,
                                     frozenset(now), False))
                elif isinstance(child, (ast.AugAssign, ast.AnnAssign)):
                    t = child.target
                    a = self_attr(t)
                    compound = isinstance(child, ast.AugAssign)
                    if a is not None and child.value is not None:
                        note_write(a, child.lineno, now, compound)
                    elif isinstance(t, ast.Subscript):
                        a = self_attr(t.value)
                        if a is not None:
                            note_write(a, child.lineno, now, True)
                    elif isinstance(t, ast.Name) and t.id in gnames and \
                            child.value is not None:
                        scan.gwrites.append((t.id, child.lineno,
                                             frozenset(now), compound))
                elif isinstance(child, ast.Delete):
                    for t in child.targets:
                        if isinstance(t, ast.Subscript):
                            a = self_attr(t.value)
                            if a is not None:
                                note_write(a, child.lineno, now, True)
                # ---- container-mutating method calls ------------------
                if isinstance(child, ast.Call) and \
                        isinstance(child.func, ast.Attribute) and \
                        child.func.attr in _MUTATOR_LEAVES:
                    a = self_attr(child.func.value)
                    if a is not None:
                        note_write(a, child.lineno, now, True)
                # ---- reads --------------------------------------------
                if isinstance(child, ast.Attribute) and \
                        isinstance(child.ctx, ast.Load):
                    a = self_attr(child)
                    if a is not None:
                        scan.reads.setdefault(a, []).append(
                            (child.lineno, frozenset(now)))
                # ---- calls --------------------------------------------
                if isinstance(child, ast.Call):
                    scan.calls.append((child, frozenset(now),
                                       child.lineno))
                visit(child, now)

        visit(fn.node, [])
        return scan

    # --------------------------------------------------- call resolution

    def _class_family(self, key: Tuple[str, str]) -> List[Tuple[str, str]]:
        """Ancestors + descendants of `key` (the dynamic-dispatch set a
        `self.m()` call can land in)."""
        fam = list(self.model.ancestry(key))
        todo = [key]
        seen = set(fam)
        while todo:
            k = todo.pop()
            for sub in self.subclasses.get(k, ()):
                if sub not in seen:
                    seen.add(sub)
                    fam.append(sub)
                    todo.append(sub)
        return fam

    def _methods_in_hierarchy(self, key: Tuple[str, str],
                              name: str) -> List[FunctionInfo]:
        out: List[FunctionInfo] = []
        for k in self._class_family(key):
            tmod = self.project.by_modpath.get(k[0])
            nodes = self.model.method_nodes.get(k, set())
            if tmod is not None:
                out.extend(g for g in tmod.functions_named(name)
                           if g.node in nodes)
        return out

    def resolve_calls(self, call: ast.Call,
                      fn: FunctionInfo) -> List[FunctionInfo]:
        """lockgraph's resolution plus cross-MODULE `self.m()` dispatch:
        `VectorIndex.build` (core/index.py) calling `self._build` must
        resolve to the BKTIndex/KDTIndex overrides in algo/ — otherwise
        every template-method `_impl` looks caller-less and its
        held-on-entry locks are lost."""
        f = call.func
        if isinstance(f, ast.Attribute) and \
                isinstance(f.value, ast.Name) and f.value.id == "self":
            key = self.model.class_of_fn.get(id(fn))
            if key is not None:
                out = self._methods_in_hierarchy(key, f.attr)
                if out:
                    return out
        return self.model.resolve_calls(call, fn)

    # ------------------------------------------------------ thread entries

    def _entry_candidates(self, call: ast.Call,
                          fn: FunctionInfo) -> List[ast.AST]:
        f = call.func
        d = _dotted(f)
        leaf = d.split(".")[-1] if d else (
            f.attr if isinstance(f, ast.Attribute) else "")
        if leaf not in _SPAWN_LEAVES:
            return []
        cands: List[ast.AST] = [kw.value for kw in call.keywords
                                if kw.arg in _SPAWN_KWARGS]
        if leaf in ("add", "submit", "apply_async", "create_task",
                    "ensure_future", "call_soon", "call_soon_threadsafe",
                    "start_server") and call.args:
            cands.append(call.args[0])
        elif leaf in ("Timer", "call_later") and len(call.args) >= 2:
            cands.append(call.args[1])
        elif leaf == "run_in_executor" and len(call.args) >= 2:
            cands.append(call.args[1])
        elif leaf == "Thread" and len(call.args) >= 2:
            cands.append(call.args[1])
        return cands

    def _resolve_callable(self, expr: ast.AST,
                          fn: FunctionInfo) -> List[FunctionInfo]:
        if isinstance(expr, ast.Call):
            # create_task(self._loop()) — the coroutine function
            return self._resolve_callable(expr.func, fn)
        d = _dotted(expr)
        mod = fn.module
        if d is None:
            # functools.partial(self._job, x) — unwrap arg 0
            if isinstance(expr, ast.Lambda):
                return []          # lambda bodies run inline; skip
            return []
        parts = d.split(".")
        if parts[0] == "self" and len(parts) == 2:
            key = self.model.class_of_fn.get(id(fn))
            if key is not None:
                out: List[FunctionInfo] = []
                for k in self.model.ancestry(key):
                    tmod = self.project.by_modpath.get(k[0])
                    nodes = self.model.method_nodes.get(k, set())
                    if tmod is not None:
                        out.extend(g for g in tmod.functions_named(parts[1])
                                   if g.node in nodes)
                if out:
                    return out
            return mod.functions_named(parts[1])
        if len(parts) == 1:
            local = mod.functions_named(d)
            if local:
                # prefer a nested def in the spawning function
                mine = [g for g in local if g.parent is fn]
                return mine or local
            target = mod.from_imports.get(d)
            if target and target.startswith(self.project.package_root):
                modpath, _, sym = target.rpartition(".")
                tmod = self.project.by_modpath.get(modpath)
                if tmod:
                    return tmod.functions_named(sym)
        if len(parts) == 2:
            full = mod.resolve_head(parts[0])
            if full and full in self.project.by_modpath:
                return self.project.by_modpath[full].functions_named(
                    parts[1])
        return []

    def _closure(self, seeds: Set[int]) -> Set[int]:
        todo = list(seeds)
        out = set(seeds)
        while todo:
            k = todo.pop()
            scan = self.scans.get(k)
            if scan is None:
                continue
            for call, _h, _l in scan.calls:
                for callee in self.resolve_calls(call, scan.fn):
                    ck = id(callee)
                    if ck in self.scans and ck not in out:
                        out.add(ck)
                        todo.append(ck)
        return out

    def _find_entries(self) -> None:
        async_entries: Set[int] = set()
        for scan in self.scans.values():
            for call, _held, _line in scan.calls:
                f = call.func
                d = _dotted(f)
                leaf = d.split(".")[-1] if d else (
                    f.attr if isinstance(f, ast.Attribute) else "")
                for cand in self._entry_candidates(call, scan.fn):
                    # functools.partial(self._job, ...): unwrap
                    if isinstance(cand, ast.Call):
                        cd = _dotted(cand.func) or ""
                        if cd.split(".")[-1] == "partial" and cand.args:
                            cand = cand.args[0]
                    for g in self._resolve_callable(cand, scan.fn):
                        self.entries.add(id(g))
                        if leaf in _THREAD_LEAVES:
                            self.thread_entries.add(id(g))
                        else:
                            async_entries.add(id(g))
        self.reachable = self._closure(self.thread_entries)
        self.async_reachable = self._closure(async_entries)

    # --------------------------------------------- held-on-entry fixpoint

    def _fixpoint_held_entry(self) -> None:
        callers: Dict[int, List[Tuple[int, FrozenSet[str]]]] = {}
        for scan in self.scans.values():
            for call, held, _line in scan.calls:
                for callee in self.resolve_calls(call, scan.fn):
                    ck = id(callee)
                    if ck in self.scans and ck != id(scan.fn):
                        callers.setdefault(ck, []).append(
                            (id(scan.fn), held))
            # a BOUND-METHOD REFERENCE (`self.m` read without a call —
            # the _blob_loaders() dispatch-table idiom) is treated as a
            # potential call from the referencing context.  Spawn
            # targets are unaffected: they are entries, and entries are
            # pinned to an empty held-set below.
            key = self.model.class_of_fn.get(id(scan.fn))
            if key is None:
                continue
            for attr, sites in scan.reads.items():
                methods = self._methods_in_hierarchy(key, attr)
                for m in methods:
                    mk = id(m)
                    if mk in self.scans and mk != id(scan.fn):
                        for _line, held in sites:
                            callers.setdefault(mk, []).append(
                                (id(scan.fn), held))
        H: Dict[int, Optional[Set[str]]] = {k: None for k in self.scans}
        for k in self.scans:
            # thread entries hold nothing on entry; so do functions with
            # no resolvable caller (the public-API / unknown case)
            if k in self.entries or k not in callers:
                H[k] = set()
        changed = True
        while changed:
            changed = False
            for k, sites in callers.items():
                if k in self.entries:
                    continue
                acc: Optional[Set[str]] = None
                for caller_id, held in sites:
                    hc = H.get(caller_id)
                    if hc is None:
                        continue          # TOP caller: no constraint yet
                    eff = set(held) | hc
                    acc = eff if acc is None else (acc & eff)
                if acc is not None and acc != H[k]:
                    if H[k] is None or acc < H[k]:
                        H[k] = acc
                        changed = True
        self.held_entry = H

    def effective_held(self, w_fn: FunctionInfo,
                       held: FrozenSet[str]) -> FrozenSet[str]:
        h = self.held_entry.get(id(w_fn))
        return held if not h else frozenset(held | h)

    # ----------------------------------------------------------- grouping

    def grouped_attrs(self) -> Dict[Tuple[Tuple[str, str], str],
                                    List[_Write]]:
        """Write sites grouped by (owner class key, attr), where owner is
        the most ancestral class in the writer's ancestry that touches
        the attribute — so `BKTIndex` and `VectorIndex` writes to one
        attribute form ONE group."""
        per_class: Dict[Tuple[str, str], Dict[str, List[_Write]]] = {}
        for scan in self.scans.values():
            key = self.model.class_of_fn.get(id(scan.fn))
            if key is None:
                continue
            slot = per_class.setdefault(key, {})
            for w in scan.writes:
                slot.setdefault(w.attr, []).append(w)
        grouped: Dict[Tuple[Tuple[str, str], str], List[_Write]] = {}
        seen: Set[Tuple[int, int]] = set()
        for key, attrs in per_class.items():
            for attr, writes in attrs.items():
                owner = key
                for k in self.model.ancestry(key):
                    if attr in per_class.get(k, {}):
                        owner = k
                group = grouped.setdefault((owner, attr), [])
                for w in writes:
                    wid = (id(w.fn), w.line)
                    if (wid + (hash(attr),)) not in seen:
                        seen.add(wid + (hash(attr),))
                        group.append(w)
        return grouped

    def grouped_reads(self) -> Dict[Tuple[Tuple[str, str], str],
                                    Dict[int, List[Tuple[int,
                                                         FrozenSet[str]]]]]:
        """(owner, attr) -> {fn_id: [(line, held)]} using the same owner
        resolution as grouped_attrs."""
        per_class_w: Dict[Tuple[str, str], Set[str]] = {}
        for scan in self.scans.values():
            key = self.model.class_of_fn.get(id(scan.fn))
            if key is None:
                continue
            per_class_w.setdefault(key, set()).update(
                w.attr for w in scan.writes)
        out: Dict[Tuple[Tuple[str, str], str],
                  Dict[int, List[Tuple[int, FrozenSet[str]]]]] = {}
        for scan in self.scans.values():
            key = self.model.class_of_fn.get(id(scan.fn))
            if key is None:
                continue
            for attr, sites in scan.reads.items():
                owner = key
                for k in self.model.ancestry(key):
                    if attr in per_class_w.get(k, set()):
                        owner = k
                out.setdefault((owner, attr), {}).setdefault(
                    id(scan.fn), []).extend(sites)
        return out

    def thread_reachable(self, fn: FunctionInfo) -> bool:
        return id(fn) in self.reachable


# ---------------------------------------------------------------------------
# guard inference (public: the runtime cross-check consumes this)
# ---------------------------------------------------------------------------

def _get_pass(project: Project) -> _Pass:
    p = project.cache.get("guardedby.pass")
    if p is None or p.project is not project:
        p = _Pass(project)
        project.cache["guardedby.pass"] = p
    return p


def infer_guards(project: Project) -> Dict[Tuple[str, str], Set[str]]:
    """{(dotted class name, attr): inferred guard lock canonicals}.

    The guard of an attribute is the intersection of the locks held at
    its locked non-``__init__`` write sites (interprocedural held-on-
    entry included); attributes with no locked write site map to an
    empty set.  tests/test_racesan.py cross-checks this against the
    locksets the runtime race sanitizer observed on a live workload.
    """
    p = _get_pass(project)
    out: Dict[Tuple[str, str], Set[str]] = {}
    for (owner, attr), writes in p.grouped_attrs().items():
        locked = [p.effective_held(w.fn, w.held)
                  for w in writes if not w.is_init]
        locked = [h for h in locked if h]
        guards: Set[str] = set()
        if locked:
            guards = set(locked[0])
            for h in locked[1:]:
                guards &= h
        out[(f"{owner[0]}.{owner[1]}", attr)] = guards
    return out


# ---------------------------------------------------------------------------
# the checks
# ---------------------------------------------------------------------------

def _fmt_guard(guards: Set[str]) -> str:
    return "/".join(sorted(guards))


def _check_attr_rules(p: _Pass) -> List[Finding]:
    out: List[Finding] = []
    for (owner, attr), writes in sorted(
            p.grouped_attrs().items(),
            key=lambda kv: (kv[0][0][0], kv[0][0][1], kv[0][1])):
        non_init = [w for w in writes if not w.is_init]
        if not non_init:
            continue
        shared = any(p.thread_reachable(w.fn) for w in non_init)
        if not shared:
            continue
        effective = [(w, p.effective_held(w.fn, w.held)) for w in non_init]
        locked = [(w, h) for w, h in effective if h]
        unlocked = [(w, h) for w, h in effective if not h]
        guards: Set[str] = set()
        if locked:
            guards = set(locked[0][1])
            for _w, h in locked[1:]:
                guards &= h
        cls = owner[1]
        # GL803: locked writers disagree about the guard entirely
        if locked and not guards and len(locked) > 1:
            seen_locks = sorted({_fmt_guard(set(h)) for _w, h in locked})
            w0 = min(locked, key=lambda wh: (wh[0].fn.module.relpath,
                                             wh[0].line))[0]
            out.append(Finding(
                "GL803", w0.fn.module.relpath, w0.line,
                f"`self.{attr}` ({cls}) is written under disjoint locks "
                f"({'; '.join(seen_locks)}) — the writers do not agree "
                "on a guard, so neither lock protects it", w0.fn.qualname))
        # GL801 / GL802 on the unlocked sites
        for w, _h in unlocked:
            if w.compound:
                out.append(Finding(
                    "GL802", w.fn.module.relpath, w.line,
                    f"unguarded read-modify-write of shared "
                    f"`self.{attr}` ({cls}) — a concurrent writer "
                    "interleaves between the read and the write "
                    "(lost update)"
                    + (f"; inferred guard: `{_fmt_guard(guards)}`"
                       if guards else ""),
                    w.fn.qualname))
            elif guards:
                out.append(Finding(
                    "GL801", w.fn.module.relpath, w.line,
                    f"unguarded write to shared `self.{attr}` ({cls}) — "
                    f"the inferred guard `{_fmt_guard(guards)}` is held "
                    f"at {len(locked)} other write site(s) but on no "
                    "interprocedural path here", w.fn.qualname))
    return out


def _check_global_rules(p: _Pass) -> List[Finding]:
    out: List[Finding] = []
    groups: Dict[Tuple[str, str],
                 List[Tuple[FunctionInfo, int, FrozenSet[str], bool]]] = {}
    for scan in p.scans.values():
        mp = p.model.modpath_of.get(id(scan.fn.module))
        if mp is None:
            continue
        for name, line, held, compound in scan.gwrites:
            groups.setdefault((mp, name), []).append(
                (scan.fn, line, held, compound))
    for (mp, name), sites in sorted(groups.items()):
        shared = any(p.thread_reachable(fn) for fn, _l, _h, _c in sites)
        if not shared:
            continue
        effective = [(fn, line, p.effective_held(fn, held), compound)
                     for fn, line, held, compound in sites]
        locked = [e for e in effective if e[2]]
        unlocked = [e for e in effective if not e[2]]
        guards: Set[str] = set()
        if locked:
            guards = set(locked[0][2])
            for e in locked[1:]:
                guards &= e[2]
        for fn, line, _h, compound in unlocked:
            if compound:
                out.append(Finding(
                    "GL802", fn.module.relpath, line,
                    f"unguarded read-modify-write of module global "
                    f"`{name}` shared with a thread"
                    + (f"; inferred guard: `{_fmt_guard(guards)}`"
                       if guards else ""), fn.qualname))
            elif guards:
                out.append(Finding(
                    "GL801", fn.module.relpath, line,
                    f"unguarded write to module global `{name}` — the "
                    f"inferred guard `{_fmt_guard(guards)}` is held at "
                    f"{len(locked)} other write site(s) but not here",
                    fn.qualname))
    return out


def _check_epoch_pin(p: _Pass) -> List[Finding]:
    out: List[Finding] = []
    reads = p.grouped_reads()
    for (owner, attr), writes in sorted(
            p.grouped_attrs().items(),
            key=lambda kv: (kv[0][0][0], kv[0][0][1], kv[0][1])):
        init_writes = [w for w in writes if w.is_init]
        swaps = [w for w in writes
                 if not w.is_init and not w.compound
                 and (p.thread_reachable(w.fn)
                      or p.effective_held(w.fn, w.held))]
        if not init_writes or not swaps:
            continue
        # the attribute must actually be swapped off-thread — a main-
        # thread-only reassign can't change under a reader's feet
        if not any(p.thread_reachable(w.fn) for w in swaps):
            continue
        guard: Set[str] = set()
        for w in swaps:
            guard |= set(p.effective_held(w.fn, w.held))
        writer_fns = {id(w.fn) for w in writes}
        for fn_id, sites in sorted(reads.get((owner, attr), {}).items()):
            scan = p.scans.get(fn_id)
            if scan is None or fn_id in writer_fns or \
                    scan.fn.name == _INIT_ONLY:
                continue
            free_lines = sorted({line for line, held in sites
                                 if not (set(p.effective_held(scan.fn,
                                                              held))
                                         & guard)})
            if len(free_lines) >= 2:
                out.append(Finding(
                    "GL804", scan.fn.module.relpath, free_lines[1],
                    f"`self.{attr}` ({owner[1]}) is swapped by a "
                    "background thread but re-read lock-free here "
                    f"(also at line {free_lines[0]}) — pin it to a "
                    "local once per call or the epochs can change "
                    "mid-call", scan.fn.qualname))
    return out


def _check_escape(p: _Pass) -> List[Finding]:
    out: List[Finding] = []
    for scan in p.scans.values():
        fn = scan.fn
        if fn.name != _INIT_ONLY or fn.parent is not None:
            continue
        key = p.model.class_of_fn.get(id(fn))
        if key is None:
            continue
        mod = fn.module
        nested = {f.node for f in mod.functions if f.parent is fn}
        # names/attrs assigned from a Thread/Timer ctor inside __init__
        handles: Set[str] = set()

        def refs_self(node: ast.AST) -> bool:
            return any(isinstance(n, ast.Name) and n.id == "self"
                       for n in ast.walk(node))

        escape: Optional[Tuple[int, str]] = None
        later_attr_writes: List[int] = []
        for node in ast.walk(fn.node):
            if node in nested:
                continue
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                t = _resolve_target(node.value.func, mod)
                leaf = (t or "").split(".")[-1]
                if leaf in ("Thread", "Timer") and refs_self(node.value):
                    for tgt in node.targets:
                        d = _dotted(tgt)
                        if d:
                            handles.add(d.split(".")[-1])
            if not isinstance(node, ast.Call):
                continue
            cands = p._entry_candidates(node, fn)
            handed = [c for c in cands if refs_self(c)] + \
                     [a for a in node.args
                      if isinstance(a, ast.Name) and a.id == "self"
                      and (_dotted(node.func) or "").split(".")[-1]
                      in _SPAWN_LEAVES]
            started = False
            if isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "start":
                base = node.func.value
                d = _dotted(base)
                if d and d.split(".")[-1] in handles:
                    started = True
                elif isinstance(base, ast.Call):
                    t = _resolve_target(base.func, mod)
                    if (t or "").split(".")[-1] in ("Thread", "Timer") \
                            and refs_self(base):
                        started = True
            leaf = (_dotted(node.func) or "").split(".")[-1]
            if (handed and leaf in _SPAWN_LEAVES and leaf not in
                    ("Thread", "Timer")) or started:
                line = node.lineno
                if escape is None or line < escape[0]:
                    escape = (line, "thread started" if started
                              else f"callable handed to `{leaf}`")
        if escape is None:
            continue
        for node in ast.walk(fn.node):
            if node in nested:
                continue
            if isinstance(node, ast.Assign) and \
                    node.lineno > escape[0]:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) and \
                            isinstance(tgt.value, ast.Name) and \
                            tgt.value.id == "self":
                        later_attr_writes.append(node.lineno)
        if later_attr_writes:
            out.append(Finding(
                "GL805", mod.relpath, escape[0],
                f"`self` escapes `{key[1]}.__init__` here ({escape[1]}) "
                "while attributes are still assigned at line(s) "
                f"{sorted(set(later_attr_writes))[:4]} — the spawned "
                "code can observe a partially-built object; publish "
                "last", fn.qualname))
    return out


#: threading ctors GL806 flags (argful Condition wraps an existing lock
#: and is canonicalized by the alias pass; semaphores have no locksan
#: wrapper and guard counting semantics, not mutual exclusion)
_PLAIN_LOCK_CTORS = {"threading.Lock", "threading.RLock"}


def _check_plain_locks(p: _Pass) -> List[Finding]:
    out: List[Finding] = []
    for mp, mod in sorted(p.project.by_modpath.items()):
        rel = mod.relpath
        if not rel.replace("\\", "/").startswith("sptag_tpu/"):
            continue
        if rel.endswith("utils/locksan.py"):
            continue              # the sanitizer cannot sanitize itself
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            t = _resolve_target(node.value.func, mod)
            flag = t in _PLAIN_LOCK_CTORS or (
                t == "threading.Condition" and not node.value.args)
            if not flag:
                continue
            tgt = node.targets[0]
            d = _dotted(tgt) or "?"
            fn = None
            for f in mod.functions:
                end = getattr(f.node, "end_lineno", f.node.lineno)
                if f.node.lineno <= node.lineno <= end:
                    fn = f
            out.append(Finding(
                "GL806", rel, node.lineno,
                f"plain `{t}()` assigned to `{d}` is invisible to the "
                "locksan runtime (order sanitizer, contention ledger, "
                "race sanitizer) — use locksan.make_lock/make_rlock "
                "with a stable name", fn.qualname if fn else ""))
    return out


def check(project: Project) -> List[Finding]:
    p = _get_pass(project)
    out: List[Finding] = []
    out.extend(_check_attr_rules(p))
    out.extend(_check_global_rules(p))
    out.extend(_check_epoch_pin(p))
    out.extend(_check_escape(p))
    out.extend(_check_plain_locks(p))
    return sorted(out, key=lambda f: (f.path, f.line, f.rule))
