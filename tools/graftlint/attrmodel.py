"""GL905/GL906 — per-class attribute model + dead-telemetry handlers.

The `iter_cost1` bug class (PR 15 root-cause): a typo'd `self.slots`
read raised AttributeError into a broad `except Exception`, silently
disabling gflops attribution FOREVER — no test failed, no log line, the
feature just never ran.  Python gives no static guarantee that an
attribute read names something ever assigned; this pass builds one per
class:

* GL905 — `self.X` (or `cls.X`) read where X is never assigned in
  `__init__`, any method, the class body, `__slots__`, or any in-project
  base class.  The severity message ESCALATES when the read sits inside
  a `try` whose broad handler swallows the AttributeError — that is the
  guaranteed-silent-death shape.  Ships with a ZERO-entry baseline: fix,
  don't waive.
* GL906 — a broad `except` (bare / `Exception` / `BaseException`)
  wrapping metric/flight/timeline/quality publishing whose handler
  neither logs, nor counts, nor re-raises: the telemetry dies and
  nothing records that it died.

Model conservatism (false negatives over false positives):

* classes with an unresolvable base (threading.Thread, pybind types,
  Protocol, ...) are skipped — externally-inherited attributes are
  invisible to the AST;
* classes containing `setattr(...)` / `__dict__` manipulation / `vars()`
  are skipped as dynamic;
* attribute names ever STORED on a non-self object anywhere in the
  project (`obj.addr = ...` — external initialization) are exempt;
* reads inside a `try` whose handler names AttributeError are exempt
  (that is the idiomatic probe for an optional attribute).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.core import (Finding, ModuleInfo, Project, _dotted)

RULES = {
    "GL905": "attribute read never assigned anywhere in the class/bases "
             "(silent AttributeError; escalated under a swallowing "
             "`except`)",
    "GL906": "broad `except` swallows telemetry publishing without "
             "logging or counting the failure",
}

#: utils modules whose calls ARE telemetry publishing (GL906 scope)
TELEMETRY_MODULES = {"metrics", "flightrec", "timeline", "trace",
                     "qualmon"}
#: call heads / attrs that count as "the handler reported the failure"
_LOG_HEADS = {"log", "logger", "logging", "warnings"}
_LOG_ATTRS = {"exception", "warning", "warn", "error", "info", "debug",
              "critical"}

_BROAD = {"Exception", "BaseException"}


@dataclasses.dataclass
class ClassModel:
    node: ast.ClassDef
    module: ModuleInfo
    qualname: str                      # module-relative dotted name
    assigned: Set[str] = dataclasses.field(default_factory=set)
    bases: List[ast.expr] = dataclasses.field(default_factory=list)
    dynamic: bool = False              # setattr/__dict__/vars seen
    resolved: Optional[Set[str]] = None   # full attr set incl. bases


def _first_param(fn: ast.AST) -> Optional[str]:
    args = getattr(fn, "args", None)
    if args is None:
        return None
    pos = args.posonlyargs + args.args
    return pos[0].arg if pos else None


def _self_names(fn: ast.AST) -> Set[str]:
    """The receiver name(s) of a method: `self` (or `cls`), skipping
    staticmethods (no receiver)."""
    for dec in getattr(fn, "decorator_list", []):
        d = _dotted(dec)
        if d and d.split(".")[-1] == "staticmethod":
            return set()
    p = _first_param(fn)
    return {p} if p else set()


def _collect_class(node: ast.ClassDef, mod: ModuleInfo,
                   qualname: str) -> ClassModel:
    model = ClassModel(node, mod, qualname, bases=list(node.bases))
    assigned = model.assigned
    for stmt in node.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            assigned.add(stmt.name)
        elif isinstance(stmt, ast.ClassDef):
            assigned.add(stmt.name)
        elif isinstance(stmt, ast.Assign):
            for tgt in stmt.targets:
                for n in ast.walk(tgt):
                    if isinstance(n, ast.Name):
                        assigned.add(n.id)
            # __slots__ entries declare instance attributes
            if any(isinstance(t, ast.Name) and t.id == "__slots__"
                   for t in stmt.targets):
                for el in ast.walk(stmt.value):
                    if isinstance(el, ast.Constant) and \
                            isinstance(el.value, str):
                        assigned.add(el.value)
        elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)) and \
                isinstance(stmt.target, ast.Name):
            assigned.add(stmt.target.id)
    # receiver-attribute stores anywhere in the class body (methods,
    # nested functions, loop targets, `with ... as self.x`, del)
    for fn in ast.walk(node):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        recv = _self_names(fn)
        if not recv:
            continue
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Attribute) and \
                    isinstance(sub.ctx, (ast.Store, ast.Del)) and \
                    isinstance(sub.value, ast.Name) and \
                    sub.value.id in recv:
                assigned.add(sub.attr)
            elif isinstance(sub, ast.Call):
                d = _dotted(sub.func)
                if d is None:
                    continue
                tail = d.split(".")[-1]
                if tail in ("setattr", "delattr", "vars") or \
                        d in ("self.__dict__.update",):
                    model.dynamic = True
            elif isinstance(sub, ast.Attribute) and \
                    sub.attr == "__dict__" and \
                    isinstance(sub.value, ast.Name) and \
                    sub.value.id in recv:
                model.dynamic = True
    return model


def _class_registry(project: Project
                    ) -> Dict[Tuple[str, str], ClassModel]:
    """{(modpath, class qualname): ClassModel} for every class."""
    cached = project.cache.get("attrmodel.registry")
    if cached is not None:
        return cached
    reg: Dict[Tuple[str, str], ClassModel] = {}
    for modpath, mod in project.by_modpath.items():
        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    qual = f"{prefix}{child.name}" if prefix \
                        else child.name
                    reg[(modpath, qual)] = _collect_class(
                        child, mod, qual)
                    visit(child, qual + ".")
                elif not isinstance(child, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                    visit(child, prefix)
                else:
                    visit(child, prefix)
        visit(mod.tree, "")
    project.cache["attrmodel.registry"] = reg
    return reg


def _resolve_base(base: ast.expr, mod: ModuleInfo, modpath: str,
                  reg: Dict[Tuple[str, str], ClassModel]
                  ) -> Optional[ClassModel]:
    """Resolve a base-class expression to an in-project ClassModel;
    None for unresolvable (external) bases.  `object` resolves to an
    empty sentinel handled by the caller."""
    d = _dotted(base)
    if d is None:
        return None
    if d == "object":
        return ClassModel(ast.ClassDef(name="object", bases=[],
                                       keywords=[], body=[],
                                       decorator_list=[]),
                          mod, "object")
    if "." not in d:
        # same module?
        m = reg.get((modpath, d))
        if m is not None:
            return m
        target = mod.from_imports.get(d)
        if target:
            tmod, _, sym = target.rpartition(".")
            return reg.get((tmod, sym))
        return None
    head, _, rest = d.partition(".")
    full = mod.resolve_head(head)
    if full is None:
        return None
    return reg.get((full, rest))


def _resolved_attrs(model: ClassModel, mod: ModuleInfo, modpath: str,
                    reg: Dict[Tuple[str, str], ClassModel],
                    _stack: Optional[Set[int]] = None
                    ) -> Optional[Set[str]]:
    """Full attribute set including bases; None = class not analyzable
    (dynamic, or an external base hides attributes)."""
    if model.resolved is not None:
        return model.resolved
    if model.dynamic:
        return None
    stack = _stack or set()
    if id(model) in stack:
        return None                     # inheritance cycle: bail out
    stack = stack | {id(model)}
    out = set(model.assigned)
    for base in model.bases:
        bm = _resolve_base(base, mod, modpath, reg)
        if bm is None:
            return None
        if bm.qualname == "object":
            continue
        bmod = bm.module
        bpath = next((p for (p, q), m in reg.items() if m is bm),
                     modpath)
        battrs = _resolved_attrs(bm, bmod, bpath, reg, stack)
        if battrs is None:
            return None
        out |= battrs
    model.resolved = out
    return out


def _external_stores(project: Project) -> Set[str]:
    """Attribute names stored on NON-receiver objects anywhere in the
    project (`server.addr = ...`) — external initialization the
    per-class model cannot see, so reads of these names are exempt."""
    cached = project.cache.get("attrmodel.external_stores")
    if cached is not None:
        return cached
    out: Set[str] = set()
    for mod in project.modules.values():
        recv_by_fn: Dict[ast.AST, Set[str]] = {}
        for fn in mod.functions:
            recv_by_fn[fn.node] = _self_names(fn.node)

        def visit(node: ast.AST, recv: Set[str]) -> None:
            for child in ast.iter_child_nodes(node):
                r = recv_by_fn.get(child, recv) \
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) else recv
                if isinstance(child, ast.Attribute) and \
                        isinstance(child.ctx, ast.Store) and not (
                            isinstance(child.value, ast.Name)
                            and child.value.id in r):
                    out.add(child.attr)
                visit(child, r)

        visit(mod.tree, set())
    project.cache["attrmodel.external_stores"] = out
    return out


def _broad_handler(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return True
    names = []
    if isinstance(h.type, ast.Tuple):
        names = [_dotted(e) or "" for e in h.type.elts]
    else:
        names = [_dotted(h.type) or ""]
    return any(n.split(".")[-1] in _BROAD for n in names)


def _names_attribute_error(h: ast.ExceptHandler) -> bool:
    if h.type is None:
        return False
    elts = h.type.elts if isinstance(h.type, ast.Tuple) else [h.type]
    return any((_dotted(e) or "").split(".")[-1] == "AttributeError"
               for e in elts)


def _handler_swallows(h: ast.ExceptHandler) -> bool:
    """True when the handler neither re-raises nor reports."""
    for n in ast.walk(h):
        if isinstance(n, ast.Raise):
            return False
        if isinstance(n, ast.Call):
            d = _dotted(n.func)
            if d is None:
                continue
            head, tail = d.split(".")[0], d.split(".")[-1]
            if head in _LOG_HEADS or tail in _LOG_ATTRS:
                return False
            if head in TELEMETRY_MODULES:
                return False
    return True


def _try_context(node: ast.AST, parents: Dict[int, ast.AST]
                 ) -> Tuple[bool, bool]:
    """(under_attributeerror_probe, under_swallowing_broad_except) for a
    read node, walking its ancestor chain: only Try nodes whose BODY
    (not handlers/finally) contains the node count."""
    probe = swallow = False
    cur = node
    while True:
        parent = parents.get(id(cur))
        if parent is None:
            break
        if isinstance(parent, ast.Try) and cur in parent.body:
            for h in parent.handlers:
                if _names_attribute_error(h):
                    probe = True
                if _broad_handler(h) and _handler_swallows(h):
                    swallow = True
        cur = parent
    return probe, swallow


def _parent_map(root: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    for node in ast.walk(root):
        for child in ast.iter_child_nodes(node):
            parents[id(child)] = node
    return parents


def _param_names(fn: ast.AST) -> Set[str]:
    a = fn.args
    names = {p.arg for p in a.posonlyargs + a.args + a.kwonlyargs}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    return names


def _receiver_loads(method: ast.AST, recv: Set[str]) -> List[ast.Attribute]:
    """Receiver-attribute Load nodes in a method, honoring closures:
    descend into nested functions only when they do NOT rebind the
    receiver name (a closure reading `self.x` is a real read of the
    enclosing instance; `def _pad(f)` reading `f.exception` is not),
    and never into nested classes (their methods have their own
    receiver and their own registry entry)."""
    out: List[ast.Attribute] = []

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                continue
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)) and \
                    recv & _param_names(child):
                continue
            if isinstance(child, ast.Attribute) and \
                    isinstance(child.ctx, ast.Load) and \
                    isinstance(child.value, ast.Name) and \
                    child.value.id in recv:
                out.append(child)
            visit(child)

    visit(method)
    return out


def _check_gl905(project: Project) -> List[Finding]:
    reg = _class_registry(project)
    external = _external_stores(project)
    out: List[Finding] = []
    for (modpath, qual), model in reg.items():
        mod = model.module
        attrs = _resolved_attrs(model, mod, modpath, reg)
        if attrs is None:
            continue
        parents = _parent_map(model.node)
        for fn in model.node.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            recv = _self_names(fn)
            if not recv:
                continue
            for sub in _receiver_loads(fn, recv):
                name = sub.attr
                if name in attrs or name.startswith("__") or \
                        name in external:
                    continue
                probe, swallow = _try_context(sub, parents)
                if probe:
                    continue
                msg = (f"`self.{name}` is never assigned anywhere in "
                       f"{qual} or its bases (AttributeError at "
                       "runtime)")
                if swallow:
                    msg += (" — and the read sits under a broad "
                            "`except` that swallows it: this failure "
                            "is GUARANTEED silent (the iter_cost1 bug "
                            "class)")
                out.append(Finding("GL905", mod.relpath, sub.lineno,
                                   msg, f"{qual}.{fn.name}"))
    return out


def _publishes_telemetry(try_node: ast.Try, mod: ModuleInfo) -> bool:
    """Does the TRY BODY (not the handlers) publish telemetry?"""
    for stmt in try_node.body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                d = _dotted(n.func)
                if d is None:
                    continue
                head = d.split(".")[0]
                full = mod.resolve_head(head) or head
                tail_mod = full.split(".")[-1]
                if tail_mod in TELEMETRY_MODULES or \
                        head in TELEMETRY_MODULES:
                    return True
    return False


def _check_gl906(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules.values():
        # enclosing-function attribution for the finding's symbol
        fn_of: Dict[int, str] = {}
        for fn in mod.functions:
            for n in ast.walk(fn.node):
                fn_of.setdefault(id(n), fn.qualname)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Try):
                continue
            if not _publishes_telemetry(node, mod):
                continue
            for h in node.handlers:
                if _broad_handler(h) and _handler_swallows(h):
                    out.append(Finding(
                        "GL906", mod.relpath, h.lineno,
                        "broad `except` around telemetry publishing "
                        "neither logs nor counts the failure — the "
                        "series dies silently (log it, count it, or "
                        "narrow the except)",
                        fn_of.get(id(h), "")))
    return out


def check(project: Project) -> List[Finding]:
    return _check_gl905(project) + _check_gl906(project)
