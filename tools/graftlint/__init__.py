"""graftlint — TPU/JAX static-analysis suite for sptag_tpu.

Five checker families, each its own module with documented rule ids:

* GL1xx  hostsync       host<->device syncs on the jitted paths
* GL2xx  retrace        recompile-per-value / per-shape hazards
* GL3xx  concurrency    unlocked shared mutation, late-binding captures
* GL4xx  errorpath      swallowed exceptions at the ErrorCode boundaries
* GL5xx  dtype_parity   integer distance paths upcasting before the dot

Run `python -m tools.graftlint sptag_tpu/` from the repo root; accepted
findings live in `baseline.toml` (every entry justified).  The runtime
complement — asserting ZERO recompiles after warmup — is
`sptag_tpu/utils/recompile_guard.py`.
"""

from tools.graftlint.core import Finding, Project  # noqa: F401

__all__ = ["Finding", "Project", "lint_project", "lint_sources",
           "ALL_RULES"]


def __getattr__(name):
    # runner imports the checker modules, which import this package —
    # lazy re-export avoids the cycle at import time
    if name in ("lint_project", "lint_sources", "ALL_RULES", "main"):
        from tools.graftlint import runner
        return getattr(runner, name)
    raise AttributeError(name)
