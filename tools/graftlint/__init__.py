"""graftlint — TPU/JAX static-analysis suite for sptag_tpu.

Checker families, each its own module with documented rule ids:

* GL1xx  hostsync       host<->device syncs on the jitted paths
* GL2xx  retrace        recompile-per-value / per-shape hazards
* GL3xx  concurrency    unlocked shared mutation, late-binding captures
* GL4xx  errorpath      swallowed exceptions at the ErrorCode boundaries
* GL5xx  dtype_parity   integer distance paths upcasting before the dot
* GL6xx  obsnames/cost  literal metric/span/stage names, cost-ledger
                        registration for jitted kernels
* GL7xx  lockgraph      lock-order cycles, blocking under a held lock,
                        leaked thread/task handles (+ GL41x persistence
                        writes outside the atomic/WAL funnel)
* GL8xx  guardedby      guarded-by inference: unguarded/inconsistent
                        writes to shared state, epoch-repin,
                        escape-before-publish, plain locks invisible
                        to the locksan runtime

Run `python -m tools.graftlint sptag_tpu/` from the repo root; accepted
findings live in `baseline.toml` (every entry justified).  The runtime
complements are `sptag_tpu/utils/recompile_guard.py` (zero recompiles
after warmup) and `sptag_tpu/utils/locksan.py` (lock-order sanitizer,
contention ledger, Eraser-style race sanitizer).
"""

from tools.graftlint.core import Finding, Project  # noqa: F401

__all__ = ["Finding", "Project", "lint_project", "lint_sources",
           "ALL_RULES"]


def __getattr__(name):
    # runner imports the checker modules, which import this package —
    # lazy re-export avoids the cycle at import time
    if name in ("lint_project", "lint_sources", "ALL_RULES", "main"):
        from tools.graftlint import runner
        return getattr(runner, name)
    raise AttributeError(name)
