"""GL9xx — device-program contract analysis (jit / shard_map / Pallas).

The ROADMAP's next tentpoles (Pallas segment-body fusion, multi-host
mesh serving) churn exactly the surface where this codebase's bugs are
silent: a per-call-varying value in a static argument recompiles on
every query, an implicit host transfer stalls the segment loop between
dispatches, a wrong collective axis name produces plausible-but-partial
merges.  This pass builds ONE project-wide model of every
`jax.jit`/`pjit`/`shard_map`/`pallas_call` site (shared through
`project.cache` with the other passes) and checks the contracts:

* GL901 — recompile hazard: a static_argnums/static_argnames position
  fed a float-derived or per-call-varying (device-tainted) value, a
  static spec that is not a literal, a static name missing from the
  wrapped signature, or a float-typed static parameter.  Extends GL2xx
  from the root's own signature to its CALL SITES.
* GL902 — implicit host sync/transfer reachable inside the
  walk/segment/scheduler hot path: interprocedural device-value taint
  through the call graph flags `.item()` / `float()` / `int()` /
  `np.asarray` / implicit `__bool__` on device values in HOST driver
  code (the scheduler cycle, segment dispatch, finalize) — the region
  GL1xx cannot see because these functions are not jit-reachable.
  `jax.device_get` (and utils.recompile_guard.device_get, the runtime
  sentinel's blessed readback) is the sanctioned explicit readback and
  KILLS the taint.
* GL903 — shard_map spec contract: literal in_specs arity vs the
  wrapped function's positional signature, literal out_specs arity vs a
  literal tuple return, and every PartitionSpec axis name against the
  mesh axes declared in the project (Mesh((...,)) literals, *_AXIS
  module constants, axis_name= call sites).
* GL904 — collective axis misuse: `psum`/`all_gather`/`ppermute`/
  `axis_index`/... whose axis name is not a declared mesh axis, or
  which executes in a function never wrapped by shard_map (unbound
  axis: a runtime NameError on the mesh, or silently wrong under a
  future pmap).

The runtime complement lives in sptag_tpu/utils/recompile_guard.py
(the trace/transfer sentinel); tests/test_tracesan.py cross-checks that
every runtime-observed transfer site is named by a GL901/GL902 finding
or a justified baseline entry.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.graftlint.core import (Finding, FunctionInfo, ModuleInfo,
                                  Project, _dotted, _is_jax_jit,
                                  _is_shard_map, body_nodes,
                                  tracer_taint)

RULES = {
    "GL901": "static jit argument fed a per-call-varying / float-derived "
             "value, or a non-literal/unknown static spec (recompile "
             "per call)",
    "GL902": "implicit host sync/transfer on a device value inside the "
             "scheduler/segment hot path (use jax.device_get / "
             "recompile_guard.device_get)",
    "GL903": "shard_map in_specs/out_specs disagree with the wrapped "
             "signature or name an undeclared mesh axis",
    "GL904": "collective axis name unbound by any enclosing "
             "shard_map/mesh declaration",
}

#: host driver functions that ARE the serving hot path (continuous
#: batching cycle, segment dispatch, finalize) — not jit-reachable, so
#: GL1xx never sees them; GL902 owns them.  Matched by simple name in
#: algo/ and parallel/ modules, then propagated over the call graph.
HOT_ROOT_NAMES = {"_cycle", "_seed_bucket", "run_segment", "seed_state",
                  "finalize", "_search_segmented"}
HOT_ROOT_DIRS = ("algo/", "parallel/")

#: explicit, sanctioned device->host readbacks (kill device taint)
_BLESSED_READBACKS = {"device_get"}

_NP_SYNC = {"asarray", "array", "copy", "frombuffer",
            "ascontiguousarray"}

#: collective -> index of its positional axis-name argument
_COLLECTIVES = {"psum": 1, "pmean": 1, "pmax": 1, "pmin": 1,
                "all_gather": 1, "ppermute": 1, "all_to_all": 1,
                "psum_scatter": 1, "axis_index": 0}


# ---------------------------------------------------------------------------
# shared model (project.cache)
# ---------------------------------------------------------------------------

class ContractModel:
    """Project-wide facts every GL9xx rule shares: module string
    constants, declared mesh axes, device-returning function names,
    hot-path reachability, shard-map reachability."""

    def __init__(self, project: Project):
        self.project = project
        self.module_strs: Dict[ModuleInfo, Dict[str, str]] = {
            mod: _module_str_constants(mod)
            for mod in project.modules.values()}
        self.declared_axes = self._collect_axes()
        self.device_returning = self._device_returning_fixpoint()
        self.hot = self._hot_reachable()
        self.shard_reachable = self._shard_reachable()

    # -- mesh axis declarations --------------------------------------------

    def _collect_axes(self) -> Set[str]:
        axes: Set[str] = set()
        for mod, consts in self.module_strs.items():
            for name, value in consts.items():
                if name.endswith("_AXIS"):
                    axes.add(value)
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func) or ""
                tail = d.split(".")[-1]
                if tail == "Mesh" and len(node.args) >= 2:
                    for el in ast.walk(node.args[1]):
                        if isinstance(el, ast.Constant) and \
                                isinstance(el.value, str):
                            axes.add(el.value)
                for kw in node.keywords:
                    if kw.arg in ("axis_name", "axis_names"):
                        for el in ast.walk(kw.value):
                            if isinstance(el, ast.Constant) and \
                                    isinstance(el.value, str):
                                axes.add(el.value)
        return axes

    def resolve_axis(self, node: ast.AST,
                     mod: ModuleInfo) -> Optional[str]:
        """A collective/PartitionSpec axis argument -> its string, when
        statically known (literal or module string constant, local or
        imported)."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        d = _dotted(node)
        if d is None:
            return None
        name = d.split(".")[-1]
        consts = self.module_strs.get(mod, {})
        if name in consts:
            return consts[name]
        target = mod.from_imports.get(name)
        if target and target.startswith(self.project.package_root):
            modpath, _, sym = target.rpartition(".")
            tmod = self.project.by_modpath.get(modpath)
            if tmod is not None:
                return self.module_strs.get(tmod, {}).get(sym)
        return None

    # -- device-returning functions ----------------------------------------

    def _device_returning_fixpoint(self) -> Set[str]:
        """Simple names of project functions whose return value holds
        device arrays.  Seeded with every jit/shard root (their outputs
        are device arrays by construction), then a fixpoint over host
        functions whose return expressions taint as device values —
        this is what carries GL902's taint ACROSS calls."""
        names: Set[str] = set()
        fns: List[FunctionInfo] = []
        for mod in self.project.modules.values():
            for fn in mod.functions:
                fns.append(fn)
                if fn.is_jit_root or fn.is_shard_root:
                    names.add(fn.name)
        for _ in range(4):                       # small fixpoint
            grew = False
            for fn in fns:
                if fn.name in names:
                    continue
                _, expr_tainted = _device_taint(fn, names)
                for node in body_nodes(fn):
                    if isinstance(node, ast.Return) and \
                            node.value is not None and \
                            expr_tainted(node.value):
                        names.add(fn.name)
                        grew = True
                        break
            if not grew:
                break
        return names

    # -- hot-path reachability ---------------------------------------------

    def _hot_reachable(self) -> Set[int]:
        seeds = []
        for mod in self.project.modules.values():
            if not any(d in mod.relpath for d in HOT_ROOT_DIRS):
                continue
            for fn in mod.functions:
                if fn.name in HOT_ROOT_NAMES and not fn.is_jit_root \
                        and not fn.is_shard_root:
                    seeds.append(fn)
        return self._propagate(seeds, stop_at_jit=True)

    def _shard_reachable(self) -> Set[int]:
        seeds = [fn for mod in self.project.modules.values()
                 for fn in mod.functions if fn.is_shard_root]
        return self._propagate(seeds, stop_at_jit=False)

    def _propagate(self, seeds: List[FunctionInfo],
                   stop_at_jit: bool) -> Set[int]:
        from tools.graftlint.core import _called_names
        seen = {id(f) for f in seeds}
        queue = list(seeds)
        while queue:
            fn = queue.pop()
            for child in fn.module.functions:
                if child.parent is fn and id(child) not in seen:
                    seen.add(id(child))
                    queue.append(child)
            for name, alias in _called_names(fn):
                for callee in self.project._resolve_call(
                        fn.module, name, alias):
                    if id(callee) in seen:
                        continue
                    if stop_at_jit and (callee.is_jit_root
                                        or callee.is_shard_root):
                        continue       # device side: GL1xx territory
                    if stop_at_jit and "utils/" in callee.module.relpath:
                        # telemetry/sentinel infrastructure is not the
                        # dispatch path (the sentinel itself handles
                        # jax objects by design)
                        continue
                    seen.add(id(callee))
                    queue.append(callee)
        return seen


def _module_str_constants(mod: ModuleInfo) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Constant) and \
                isinstance(node.value.value, str):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value.value
    return out


def get_model(project: Project) -> ContractModel:
    model = project.cache.get("tracecontract.model")
    if model is None or model.project is not project:
        model = ContractModel(project)
        project.cache["tracecontract.model"] = model
    return model


# ---------------------------------------------------------------------------
# device-value taint for HOST functions (GL902's evaluator)
# ---------------------------------------------------------------------------

def _is_blessed_readback(call: ast.Call) -> bool:
    d = _dotted(call.func)
    return d is not None and d.split(".")[-1] in _BLESSED_READBACKS


def _device_taint(fn: FunctionInfo, device_returning: Set[str]):
    """(tainted_names, expr_tainted) for a HOST function: which local
    names hold device arrays.  Seeds are jnp./jax. producing calls and
    calls to device-returning project functions (by simple name — this
    is what makes the analysis interprocedural: `engine.run_segment`
    taints even though `engine` is a local object the alias table
    cannot resolve).  `jax.device_get(...)` is host."""
    mod = fn.module
    tainted: Set[str] = set()

    def call_taints(node: ast.Call) -> bool:
        if _is_blessed_readback(node):
            return False
        d = _dotted(node.func)
        if d is not None:
            head = d.split(".")[0]
            tail = d.split(".")[-1]
            full = mod.resolve_head(head)
            if full is not None:
                base = full.split(".")[0]
                if base == "numpy":
                    return False           # host result
                if base == "jax":
                    from tools.graftlint.core import \
                        _is_jax_producing_call
                    return _is_jax_producing_call(node, mod)
            if tail in device_returning:
                return True
            if head == "len" or tail == "len":
                return False
        return any(expr_tainted(a) for a in node.args) or \
            any(expr_tainted(k.value) for k in node.keywords)

    def expr_tainted(node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Attribute):
            from tools.graftlint.core import STATIC_ATTRS
            if node.attr in STATIC_ATTRS:
                return False
            return expr_tainted(node.value)
        if isinstance(node, ast.Call):
            return call_taints(node)
        if isinstance(node, ast.BinOp):
            return expr_tainted(node.left) or expr_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return expr_tainted(node.operand)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot))
                   for op in node.ops):
                return False
            return expr_tainted(node.left) or \
                any(expr_tainted(c) for c in node.comparators)
        if isinstance(node, ast.Subscript):
            return expr_tainted(node.value)
        if isinstance(node, ast.IfExp):
            return expr_tainted(node.body) or expr_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(expr_tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(v is not None and expr_tainted(v)
                       for v in node.values)
        if isinstance(node, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp)):
            return expr_tainted(node.elt)
        if isinstance(node, ast.DictComp):
            return expr_tainted(node.value)
        if isinstance(node, ast.Starred):
            return expr_tainted(node.value)
        return False

    nested = {f.node for f in mod.functions if f.parent is fn}

    def bind(tgt: ast.AST, is_tainted: bool) -> None:
        # only the names being BOUND change state: a subscript or
        # attribute store mutates an existing container (a numpy
        # out-buffer filled from a device value stays numpy), and
        # index expressions inside the target are reads, not binds
        if isinstance(tgt, ast.Name):
            if is_tainted:
                tainted.add(tgt.id)
            else:
                tainted.discard(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                bind(el, is_tainted)
        elif isinstance(tgt, ast.Starred):
            bind(tgt.value, is_tainted)

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if child in nested:
                continue
            if isinstance(child, ast.Assign):
                t = expr_tainted(child.value)
                for tgt in child.targets:
                    bind(tgt, t)
            elif isinstance(child, (ast.AugAssign, ast.AnnAssign)) and \
                    child.value is not None and \
                    expr_tainted(child.value):
                bind(child.target, True)
            visit(child)

    # two forward passes: the scheduler's cycle assigns through dicts
    # and tuple unpacking where one pass misses loop-carried names
    visit(fn.node)
    visit(fn.node)
    return tainted, expr_tainted


# ---------------------------------------------------------------------------
# GL901 — recompile hazards at jit sites and their call sites
# ---------------------------------------------------------------------------

def _static_spec_issues(call: ast.Call) -> List[str]:
    """Non-literal static_argnames/static_argnums specs (core's
    extractor silently ignores them, so nothing downstream would ever
    know the spec existed)."""
    issues = []
    for kw in call.keywords:
        if kw.arg not in ("static_argnames", "static_argnums"):
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and \
                isinstance(v.value, (str, int)):
            continue
        if isinstance(v, (ast.Tuple, ast.List, ast.Set)):
            bad = [e for e in v.elts
                   if not (isinstance(e, ast.Constant)
                           and isinstance(e.value, (str, int)))]
            if bad:
                issues.append(
                    f"{kw.arg} contains non-literal entries — the "
                    "static set cannot be checked (or reproduced) "
                    "statically")
            continue
        issues.append(
            f"{kw.arg} is not a literal — the static set cannot be "
            "checked statically")
    return issues


def _float_static_params(fn: FunctionInfo) -> List[Tuple[str, str]]:
    """(param, why) for static params that are float-typed: every
    distinct float mints a new executable (GL2xx quantizes ints; floats
    have no ladder)."""
    out = []
    a = fn.node.args
    params = a.posonlyargs + a.args + a.kwonlyargs
    defaults = list(a.defaults)
    dmap: Dict[str, ast.expr] = {}
    pos = a.posonlyargs + a.args
    for p, dflt in zip(pos[len(pos) - len(defaults):], defaults):
        dmap[p.arg] = dflt
    for p, dflt in zip(a.kwonlyargs, a.kw_defaults):
        if dflt is not None:
            dmap[p.arg] = dflt
    for p in params:
        if p.arg not in fn.static_args:
            continue
        ann = getattr(p, "annotation", None)
        if ann is not None and (_dotted(ann) or "") == "float":
            out.append((p.arg, "annotated `float`"))
            continue
        d = dmap.get(p.arg)
        if isinstance(d, ast.Constant) and isinstance(d.value, float):
            out.append((p.arg, "float default"))
    return out


def _float_derived(node: ast.AST) -> bool:
    """Is this call-site argument float-derived (a fresh float per
    call)?  Literal floats are fine — they are the SAME value every
    call; what recompiles is arithmetic minting a new float."""
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
        return True
    if isinstance(node, ast.Call):
        d = _dotted(node.func) or ""
        if d.split(".")[0] == "time" or d.split(".")[-1] == "float":
            return True
    return False


def _check_gl901(project: Project, model: ContractModel
                 ) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules.values():
        path = mod.relpath
        # enclosing-function attribution
        fn_of: Dict[int, str] = {}
        for fn in mod.functions:
            for n in ast.walk(fn.node):
                fn_of.setdefault(id(n), fn.qualname)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            is_jit_call = _is_jax_jit(node.func, mod) or (
                (_dotted(node.func) or "") in
                ("functools.partial", "partial") and node.args
                and _is_jax_jit(node.args[0], mod))
            if not is_jit_call:
                continue
            for issue in _static_spec_issues(node):
                out.append(Finding("GL901", path, node.lineno, issue,
                                   fn_of.get(id(node), "")))
    # static names vs signatures, float-typed static params
    for mod in project.modules.values():
        for fn in mod.functions:
            if not fn.is_jit_root or not fn.static_args:
                continue
            params = set(fn.param_names())
            for name in sorted(fn.static_args - params):
                out.append(Finding(
                    "GL901", mod.relpath, fn.line,
                    f"static arg {name!r} is not a parameter of "
                    f"{fn.name} — the spec silently binds nothing",
                    fn.qualname))
            for pname, why in _float_static_params(fn):
                out.append(Finding(
                    "GL901", mod.relpath, fn.line,
                    f"static param {pname!r} is float-typed ({why}): "
                    "every distinct value compiles a new executable "
                    "(quantize to an int ladder, or pass it traced)",
                    fn.qualname))
    # call sites feeding static positions
    for mod in project.modules.values():
        for caller in mod.functions:
            taint_expr = None
            for node in body_nodes(caller):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Name):
                    name, alias = f.id, None
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name):
                    name, alias = f.attr, f.value.id
                else:
                    continue
                for callee in project._resolve_call(mod, name, alias):
                    if not callee.is_jit_root or not callee.static_args:
                        continue
                    params = callee.param_names()
                    feeds: List[Tuple[str, ast.AST]] = []
                    for i, arg in enumerate(node.args):
                        if i < len(params) and \
                                params[i] in callee.static_args:
                            feeds.append((params[i], arg))
                    for kw in node.keywords:
                        if kw.arg in callee.static_args:
                            feeds.append((kw.arg, kw.value))
                    for pname, arg in feeds:
                        if isinstance(arg, (ast.List, ast.Dict,
                                            ast.Set)):
                            out.append(Finding(
                                "GL901", mod.relpath, node.lineno,
                                f"static arg {pname!r} of "
                                f"{callee.name} fed a mutable "
                                "list/dict/set literal (unhashable; "
                                "and mutation would not retrigger a "
                                "trace)", caller.qualname))
                            continue
                        if _float_derived(arg):
                            out.append(Finding(
                                "GL901", mod.relpath, node.lineno,
                                f"static arg {pname!r} of "
                                f"{callee.name} fed a float-derived "
                                "value — a fresh float per call means "
                                "a fresh compile per call",
                                caller.qualname))
                            continue
                        if taint_expr is None:
                            if caller.jit_reachable:
                                tracer_taint(caller)
                                taint_expr = caller._taint_expr
                            else:
                                _, taint_expr = _device_taint(
                                    caller, model.device_returning)
                        if taint_expr(arg):
                            out.append(Finding(
                                "GL901", mod.relpath, node.lineno,
                                f"static arg {pname!r} of "
                                f"{callee.name} fed a device value — "
                                "it varies per call, so every call "
                                "re-traces (pass it traced, or read "
                                "it back explicitly once)",
                                caller.qualname))
    return out


# ---------------------------------------------------------------------------
# GL902 — implicit host sync in the hot path
# ---------------------------------------------------------------------------

def _np_alias_heads(mod: ModuleInfo) -> Set[str]:
    return {alias for alias, full in mod.import_aliases.items()
            if full.split(".")[0] == "numpy"}


def _check_gl902(project: Project, model: ContractModel
                 ) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules.values():
        np_heads = _np_alias_heads(mod)
        for fn in mod.functions:
            if id(fn) not in model.hot or fn.jit_reachable:
                continue
            _, expr_tainted = _device_taint(fn, model.device_returning)
            path = mod.relpath
            for node in body_nodes(fn):
                if isinstance(node, ast.Call):
                    f = node.func
                    if isinstance(f, ast.Attribute) and \
                            f.attr == "item" and not node.args and \
                            expr_tainted(f.value):
                        out.append(Finding(
                            "GL902", path, node.lineno,
                            "`.item()` on a device value inside the "
                            "hot path blocks the dispatch pipeline "
                            "(read back explicitly with "
                            "jax.device_get outside the loop)",
                            fn.qualname))
                    elif isinstance(f, ast.Name) and \
                            f.id in ("float", "int") and \
                            len(node.args) == 1 and \
                            expr_tainted(node.args[0]):
                        out.append(Finding(
                            "GL902", path, node.lineno,
                            f"`{f.id}()` on a device value inside the "
                            "hot path forces a blocking sync per call",
                            fn.qualname))
                    elif isinstance(f, ast.Attribute) and \
                            f.attr in _NP_SYNC and \
                            isinstance(f.value, ast.Name) and \
                            f.value.id in np_heads and node.args and \
                            expr_tainted(node.args[0]):
                        out.append(Finding(
                            "GL902", path, node.lineno,
                            f"`{f.value.id}.{f.attr}()` on a device "
                            "value inside the hot path is an IMPLICIT "
                            "device->host transfer — use "
                            "jax.device_get (the sanctioned explicit "
                            "readback the transfer sentinel allows)",
                            fn.qualname))
                elif isinstance(node, (ast.If, ast.While)) and \
                        expr_tainted(node.test):
                    kw = "if" if isinstance(node, ast.If) else "while"
                    out.append(Finding(
                        "GL902", path, node.lineno,
                        f"`{kw}` on a device value inside the hot "
                        "path implicitly syncs per iteration "
                        "(device_get the flag once, or fold the "
                        "branch into the kernel)", fn.qualname))
                elif isinstance(node, ast.BoolOp) and \
                        any(expr_tainted(v) for v in node.values):
                    out.append(Finding(
                        "GL902", path, node.lineno,
                        "`and`/`or` on a device value inside the hot "
                        "path coerces it to bool (a blocking sync)",
                        fn.qualname))
    return out


# ---------------------------------------------------------------------------
# GL903 / GL904 — shard_map spec + collective axis contracts
# ---------------------------------------------------------------------------

def _pspec_axes(spec_node: ast.AST, mod: ModuleInfo,
                model: ContractModel) -> List[Tuple[str, int]]:
    """(axis, lineno) for every axis name inside PartitionSpec calls in
    a spec expression."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(spec_node):
        if not isinstance(node, ast.Call):
            continue
        tail = (_dotted(node.func) or "").split(".")[-1]
        if tail not in ("P", "PartitionSpec"):
            continue
        for arg in node.args:
            elts = arg.elts if isinstance(arg, ast.Tuple) else [arg]
            for el in elts:
                if isinstance(el, ast.Constant) and el.value is None:
                    continue
                axis = model.resolve_axis(el, mod)
                if axis is not None:
                    out.append((axis, node.lineno))
    return out


def _positional_param_count(fn: FunctionInfo) -> int:
    a = fn.node.args
    n = len(a.posonlyargs) + len(a.args)
    if a.posonlyargs and a.posonlyargs[0].arg == "self":
        n -= 1
    elif a.args and a.args[0].arg == "self":
        n -= 1
    return n


def _check_gl903(project: Project, model: ContractModel
                 ) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules.values():
        fn_of: Dict[int, str] = {}
        for fn in mod.functions:
            for n in ast.walk(fn.node):
                fn_of.setdefault(id(n), fn.qualname)
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and _is_shard_map(node.func, mod) and node.args):
                continue
            sym = fn_of.get(id(node), "")
            kw = {k.arg: k.value for k in node.keywords}
            in_specs = kw.get("in_specs")
            out_specs = kw.get("out_specs")
            if in_specs is None and len(node.args) >= 3:
                in_specs = node.args[2]
            if out_specs is None and len(node.args) >= 4:
                out_specs = node.args[3]
            wrapped = None
            if isinstance(node.args[0], ast.Name):
                wname = node.args[0].id
                cands = mod.functions_named(wname)
                # several kernels each nest a `local` — bind to the one
                # scoped under the ENCLOSING function, not the first
                scoped = [c for c in cands
                          if sym and c.qualname == f"{sym}.{wname}"]
                if scoped:
                    wrapped = scoped[0]
                elif len(cands) == 1:
                    wrapped = cands[0]
            if wrapped is not None and \
                    isinstance(in_specs, ast.Tuple):
                want = _positional_param_count(wrapped)
                got = len(in_specs.elts)
                if got != want:
                    out.append(Finding(
                        "GL903", mod.relpath, node.lineno,
                        f"in_specs has {got} spec(s) but "
                        f"{wrapped.name} takes {want} positional "
                        "argument(s) — the mapping is misaligned",
                        sym))
            if wrapped is not None and \
                    isinstance(out_specs, ast.Tuple):
                rets = [n2 for n2 in body_nodes(wrapped)
                        if isinstance(n2, ast.Return)
                        and n2.value is not None]
                tuple_lens = {len(r.value.elts) for r in rets
                              if isinstance(r.value, ast.Tuple)}
                if len(rets) and len(tuple_lens) == 1 and \
                        all(isinstance(r.value, ast.Tuple)
                            for r in rets):
                    want = tuple_lens.pop()
                    got = len(out_specs.elts)
                    if got != want:
                        out.append(Finding(
                            "GL903", mod.relpath, node.lineno,
                            f"out_specs has {got} spec(s) but "
                            f"{wrapped.name} returns {want} value(s)",
                            sym))
            if model.declared_axes:
                for spec in (in_specs, out_specs):
                    if spec is None:
                        continue
                    for axis, line in _pspec_axes(spec, mod, model):
                        if axis not in model.declared_axes:
                            out.append(Finding(
                                "GL903", mod.relpath, line,
                                f"PartitionSpec axis {axis!r} is not "
                                "a declared mesh axis "
                                f"({sorted(model.declared_axes)})",
                                sym))
    return out


def _check_gl904(project: Project, model: ContractModel
                 ) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules.values():
        for fn in mod.functions:
            for node in body_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                d = _dotted(node.func)
                if d is None:
                    continue
                tail = d.split(".")[-1]
                if tail not in _COLLECTIVES:
                    continue
                head = d.split(".")[0]
                full = mod.resolve_head(head) or head
                if not (full.split(".")[0] == "jax" or head in
                        ("lax", "jax") or
                        (mod.from_imports.get(tail, "")
                         .startswith("jax"))):
                    continue
                axis_node = None
                for kw in node.keywords:
                    if kw.arg == "axis_name":
                        axis_node = kw.value
                idx = _COLLECTIVES[tail]
                if axis_node is None and len(node.args) > idx:
                    axis_node = node.args[idx]
                axis = (model.resolve_axis(axis_node, mod)
                        if axis_node is not None else None)
                if id(fn) not in model.shard_reachable:
                    out.append(Finding(
                        "GL904", mod.relpath, node.lineno,
                        f"collective `{tail}` executes in a function "
                        "never wrapped by shard_map — its axis "
                        f"{axis!r} is unbound at trace time",
                        fn.qualname))
                elif axis is not None and model.declared_axes and \
                        axis not in model.declared_axes:
                    out.append(Finding(
                        "GL904", mod.relpath, node.lineno,
                        f"collective `{tail}` names axis {axis!r}, "
                        "which no mesh declaration binds "
                        f"({sorted(model.declared_axes)})",
                        fn.qualname))
    return out


def check(project: Project) -> List[Finding]:
    model = get_model(project)
    return (_check_gl901(project, model)
            + _check_gl902(project, model)
            + _check_gl903(project, model)
            + _check_gl904(project, model))
