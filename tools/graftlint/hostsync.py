"""GL1xx — host-sync lint.

A host<->device synchronization inside the jitted search/build paths either
fails at trace time (implicit tracer->bool) or, worse, silently forces a
blocking device readback per call (`.item()`, `float()`, `np.asarray` on a
committed array in the surrounding host code) — exactly the per-query sync
TPU-KNN (arXiv:2206.14286) shows destroys peak-FLOP/s serving.  All rules
run only over functions REACHABLE from a jit/shard_map root (core.py).

Rules:

* GL101 — `.item()` call inside a jit-reachable function.  On a tracer it
  is a trace-time error; on a concrete array it is a device sync.
* GL102 — `float()` / `int()` / `bool()` applied to a (statically) traced
  value.  Static arguments and shape-derived ints are exempt via the
  taint analysis.
* GL103 — `np.asarray` / `np.array` / `np.copy` inside a jit-reachable
  function: forces a host transfer mid-program (trace-time error under
  jit; a silent sync in the op-by-op fallback).
* GL104 — implicit tracer->bool: an `if` / `while` test or `and`/`or`/
  `not` operand that taints as a traced value.  Use `jnp.where` /
  `lax.cond` / `lax.select` instead.
"""

from __future__ import annotations

import ast
from typing import List

from tools.graftlint.core import (
    Finding,
    FunctionInfo,
    Project,
    _dotted,
    body_nodes,
    tracer_taint,
)

RULES = {
    "GL101": "`.item()` inside a jit-reachable function (host sync)",
    "GL102": "float()/int()/bool() on a traced jax value (host sync)",
    "GL103": "np.asarray/np.array inside a jit-reachable function "
             "(host transfer)",
    "GL104": "implicit tracer-to-bool in `if`/`while`/boolean op "
             "(trace-time error / per-call sync)",
}

_CASTS = {"float", "int", "bool"}
_NP_SYNC = {"asarray", "array", "copy", "frombuffer", "ascontiguousarray"}


def _np_alias_heads(fn: FunctionInfo) -> set:
    return {alias for alias, full in fn.module.import_aliases.items()
            if full.split(".")[0] == "numpy"}


def _check_function(fn: FunctionInfo) -> List[Finding]:
    out: List[Finding] = []
    path = fn.module.relpath
    tainted = tracer_taint(fn, inherited=_inherited(fn))
    expr_tainted = fn._taint_expr
    np_heads = _np_alias_heads(fn)

    for node in body_nodes(fn):
        if isinstance(node, ast.Call):
            f = node.func
            # GL101: .item()
            if isinstance(f, ast.Attribute) and f.attr == "item" \
                    and not node.args:
                out.append(Finding(
                    "GL101", path, node.lineno,
                    "`.item()` forces a blocking device->host sync",
                    fn.qualname))
            # GL102: float()/int()/bool() on traced value
            elif isinstance(f, ast.Name) and f.id in _CASTS \
                    and len(node.args) == 1 and expr_tainted(node.args[0]):
                out.append(Finding(
                    "GL102", path, node.lineno,
                    f"`{f.id}()` on a traced jax value syncs the device "
                    "(use the array itself, or declare the input static)",
                    fn.qualname))
            # GL103: np.asarray / np.array
            elif isinstance(f, ast.Attribute) and f.attr in _NP_SYNC and \
                    isinstance(f.value, ast.Name) and f.value.id in np_heads:
                out.append(Finding(
                    "GL103", path, node.lineno,
                    f"`{f.value.id}.{f.attr}()` inside a jit-reachable "
                    "function forces a host transfer (keep the hot path "
                    "in jnp)", fn.qualname))
        # GL104: implicit tracer-to-bool
        elif isinstance(node, (ast.If, ast.While)) and \
                expr_tainted(node.test):
            kw = "if" if isinstance(node, ast.If) else "while"
            out.append(Finding(
                "GL104", path, node.lineno,
                f"`{kw}` on a traced value is a trace-time error (use "
                "jnp.where / lax.cond)", fn.qualname))
        elif isinstance(node, ast.BoolOp) and \
                any(expr_tainted(v) for v in node.values):
            out.append(Finding(
                "GL104", path, node.lineno,
                "`and`/`or` on a traced value coerces it to bool (use "
                "`&`/`|`)", fn.qualname))
        elif isinstance(node, ast.UnaryOp) and \
                isinstance(node.op, ast.Not) and expr_tainted(node.operand):
            out.append(Finding(
                "GL104", path, node.lineno,
                "`not` on a traced value coerces it to bool (use `~`)",
                fn.qualname))
        elif isinstance(node, ast.Assert) and expr_tainted(node.test):
            out.append(Finding(
                "GL104", path, node.lineno,
                "`assert` on a traced value coerces it to bool "
                "(use checkify or a host-side check)", fn.qualname))
    # silence the "tainted unused" style complaint — the closure uses it
    del tainted
    return out


def _inherited(fn: FunctionInfo):
    """Nested defs see the enclosing function's taint (closure capture)."""
    chain = []
    p = fn.parent
    while p is not None:
        chain.append(p)
        p = p.parent
    inherited = set()
    for anc in reversed(chain):
        inherited = tracer_taint(anc, inherited=inherited)
    return inherited


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for fn in project.jit_reachable_functions():
        out.extend(_check_function(fn))
    return out
