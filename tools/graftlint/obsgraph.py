"""GL10xx — the observability/config contract graph (ObsModel).

Six string-keyed telemetry/config planes are produced in one module and
consumed by literal name in another: the metrics registry, labeled
families, timeline series, flight-recorder kinds, the /debug route
registry, and the INI/param surface.  GL601-609 prove every such name is
a *literal*; nothing proved that the literal on the consuming side
matches one on the producing side — the two worst recent bugs were
exactly this class (the dead `iter_cost1` gflops attribution; the SLO
engine reading ``aggregator.requests.rate`` where the producer publishes
``aggregator.request.rate``).

This pass builds a project-wide **ObsModel** (cached in
``project.cache`` alongside the ContractModel) with every producer and
consumer site, then cross-checks the dataflow:

* producers — ``metrics.counter/gauge/histogram`` (+ the ``inc`` /
  ``set_gauge`` / ``observe`` conveniences and ``trace.span/record``,
  which feed the same registry), ``metrics.Family`` constructions with
  their label-key sets (including bounded-loop expansions such as
  ``Family("flight." + key) for key in _FLIGHT_KEYS``),
  ``timeline.record`` series, ``flightrec.record/span`` kinds,
  ``ctlaudit.record`` rules, the metrics_http ``_routes`` registry,
  ``core/params`` specs + the ``LIVE_ACTUATIONS`` registry, and the
  qualmon triage-verdict classifier returns;
* consumers — ``timeline.latest/window_values/window_stats/points``
  reads (the SLO engine's ``_Objective`` series lists are expanded
  through a bounded string evaluator that understands concatenation
  and refined ``base == "server"`` conditionals),
  ``metrics.counter_value/gauge_value/histogram_or_none`` reads,
  benchdiff's metric catalog, hostprof's ``EXPECTED_ROUTES``
  (tests/test_hostprof.py), docs/PARAMETERS.md rows, and
  ``[Service]``/``[Aggregator]``/``[Index]``/``[QueryConfig]`` INI key
  parsing.

Series derivation is modeled, not guessed: a registry counter ``X``
exists on the timeline as ``X.rate``; a histogram as ``X.p50_ms`` /
``X.p99_ms`` / ``X.rate``; a gauge as ``X``; a family sample with
labels as ``X{k="v"}`` and without as bare ``X`` (utils/timeline.py
``sample_now``).

Rules:

* GL1001 — a consumed name is never published by any producer (the
  PR 15 ``aggregator.requests.rate`` bug class; error tier).  Includes
  kind mismatches (``counter_value`` of a gauge) and triage verdicts
  returned by the classifier but missing from ``TRIAGE_VERDICTS``.
* GL1002 — a published name is never consumed by a structured reader
  AND never mentioned in docs/tests/tools (warn tier; a justified
  baseline entry is the sanctioned waiver).  Also flags a
  ``TRIAGE_VERDICTS`` registry entry no classifier can return.
* GL1003 — producer/consumer label-set mismatch on a family: two
  producer sites publish the same family with different label-key
  sets, or a consumer reads the BARE series name of a family that only
  ever publishes labeled samples (the bare timeline key would never
  receive a point).
* GL1004 — config-surface/doc drift: a core/params spec or live
  actuation without a PARAMETERS.md mention, a PARAMETERS.md table row
  naming no spec/actuation, or a parsed serve-tier INI key
  (``[Service]``/``[Aggregator]``/``[QueryConfig]``) PARAMETERS.md
  never documents.
* GL1005 — a literal param name at a ``set_parameter`` / ``get_param``
  / actuation call site with no backing spec or registry entry, or an
  index-scoped ``LIVE_ACTUATIONS`` entry whose name matches no
  ParamSpec (the actuation would raise at apply time).
* GL1006 — a /debug route registered in metrics_http's ``_routes``
  but absent from ``EXPECTED_ROUTES`` (or vice versa): the
  route-contract tests would silently skip the new endpoint.

Cross-tree surfaces (docs/PARAMETERS.md, tests/test_hostprof.py,
tools/benchdiff.py, bench.py) are consulted only for disk-backed
projects (``project.source_root``); in-memory fixture projects may
plant them via ``extra_sources`` (a ``docs/PARAMETERS.md`` key) or
in-project assignments (``EXPECTED_ROUTES = [...]``).  The runtime
complement lives in tools/graftlint/schemadump.py (`--schema-dump`).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from tools.graftlint.core import Finding, ModuleInfo, Project, _dotted

RULES = {
    "GL1001": "consumed observability/config name is never published by "
              "any producer (stale or typo'd consumer literal)",
    "GL1002": "published name is never consumed and never documented "
              "(warn tier; justify in the baseline or delete it)",
    "GL1003": "producer/consumer label-set mismatch on a metric family",
    "GL1004": "param/config surface and docs/PARAMETERS.md disagree "
              "(spec without a doc row, or doc row without a spec)",
    "GL1005": "param name used or actuation registered with no backing "
              "spec/registry entry",
    "GL1006": "/debug route registry and EXPECTED_ROUTES disagree",
}

CACHE_KEY = "obsgraph.model"

_METRICS_MODULE = "sptag_tpu.utils.metrics"
_TRACE_MODULE = "sptag_tpu.utils.trace"
_TIMELINE_MODULE = "sptag_tpu.utils.timeline"
_FLIGHT_MODULE = "sptag_tpu.utils.flightrec"
_QUALMON_MODULE = "sptag_tpu.utils.qualmon"
_CTLAUDIT_MODULE = "sptag_tpu.serve.ctlaudit"
_PARAMS_MODULE = "sptag_tpu.core.params"

#: expansion caps for the bounded string evaluator — anything bigger is
#: treated as unbounded (the GL60x literal rules already bound the raw
#: call-site surface; the evaluator only needs small closed sets)
_MAX_SET = 64

_IDENTISH = re.compile(r"^[A-Za-z_][A-Za-z0-9_.\[\]]*$")
_BACKTICK = re.compile(r"`([^`]+)`")


@dataclasses.dataclass(frozen=True, order=True)
class Site:
    path: str
    line: int
    symbol: str = ""


# ---------------------------------------------------------------------------
# bounded string evaluation
# ---------------------------------------------------------------------------

class _Env:
    """Best-effort, bounded string-set bindings for one function scope:
    module-level str constants, simple local assignments, and for-loop/
    comprehension targets iterating literal tuples of constants.  A
    lookup answers "which strings can this name hold" or None for
    unbounded."""

    def __init__(self, mod: ModuleInfo, fn_node: Optional[ast.AST]):
        self.mod = mod
        self.assigns: Dict[str, List[ast.AST]] = {}
        self.loops: Dict[str, Optional[Set[str]]] = {}
        self.tuples: Dict[str, ast.AST] = {}
        self._module_bindings()
        if fn_node is not None:
            self._scope_bindings(fn_node)

    def _module_bindings(self) -> None:
        for node in self.mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if isinstance(node.value, ast.Constant) and \
                        isinstance(node.value.value, str):
                    self.assigns.setdefault(name, []).append(node.value)
                elif isinstance(node.value, (ast.Tuple, ast.List)):
                    self.tuples[name] = node.value

    def _scope_bindings(self, fn_node: ast.AST) -> None:
        for node in ast.walk(fn_node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if isinstance(tgt, ast.Name):
                    if isinstance(node.value, (ast.Tuple, ast.List)):
                        self.tuples[tgt.id] = node.value
                    else:
                        self.assigns.setdefault(tgt.id, []) \
                            .append(node.value)
            elif isinstance(node, ast.For):
                self._bind_loop(node.target, node.iter)
            elif isinstance(node, (ast.ListComp, ast.SetComp,
                                   ast.GeneratorExp, ast.DictComp)):
                for gen in node.generators:
                    self._bind_loop(gen.target, gen.iter)

    def _rows(self, iter_expr: ast.AST) -> Optional[List[ast.AST]]:
        if isinstance(iter_expr, (ast.Tuple, ast.List)):
            return list(iter_expr.elts)
        if isinstance(iter_expr, ast.Name):
            lit = self.tuples.get(iter_expr.id)
            if lit is not None:
                return list(lit.elts)
        return None

    def _bind_loop(self, target: ast.AST, iter_expr: ast.AST) -> None:
        rows = self._rows(iter_expr)
        targets: List[ast.AST] = (
            list(target.elts) if isinstance(target, ast.Tuple)
            else [target])
        for i, tgt in enumerate(targets):
            if not isinstance(tgt, ast.Name):
                continue
            if rows is None:
                self.loops.setdefault(tgt.id, None)
                continue
            vals: Optional[Set[str]] = set()
            for row in rows:
                elt = row
                if isinstance(target, ast.Tuple):
                    if isinstance(row, (ast.Tuple, ast.List)) and \
                            i < len(row.elts):
                        elt = row.elts[i]
                    else:
                        vals = None
                        break
                if isinstance(elt, ast.Constant) and \
                        isinstance(elt.value, str):
                    vals.add(elt.value)
                elif isinstance(elt, ast.Constant):
                    continue          # non-str constant: not a name source
                else:
                    vals = None
                    break
            self.loops[tgt.id] = vals

    def lookup(self, name: str, overlay: Dict[str, Optional[Set[str]]],
               seen: FrozenSet[str]) -> Optional[Set[str]]:
        if name in overlay:
            return overlay[name]
        if name in seen:
            return None
        if name in self.loops:
            return self.loops[name]
        if name in self.assigns:
            out: Set[str] = set()
            for expr in self.assigns[name]:
                vals = eval_str_set(expr, self, overlay,
                                    seen | frozenset([name]))
                if vals is None:
                    return None
                out |= vals
            return out if out and len(out) <= _MAX_SET else None
        return None


def eval_str_set(expr: ast.AST, env: _Env,
                 overlay: Optional[Dict[str, Optional[Set[str]]]] = None,
                 seen: FrozenSet[str] = frozenset()
                 ) -> Optional[Set[str]]:
    """The bounded set of strings `expr` can evaluate to, or None."""
    overlay = overlay or {}
    if isinstance(expr, ast.Constant):
        return {expr.value} if isinstance(expr.value, str) else None
    if isinstance(expr, ast.Name):
        return env.lookup(expr.id, overlay, seen)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = eval_str_set(expr.left, env, overlay, seen)
        right = eval_str_set(expr.right, env, overlay, seen)
        if left is None or right is None:
            return None
        out = {a + b for a in left for b in right}
        return out if len(out) <= _MAX_SET else None
    if isinstance(expr, ast.IfExp):
        # refined-branch evaluation: `X + ".a" if base == "server" else
        # Y` must not leak the "aggregator" binding into the body arm
        body_overlay, orelse_overlay = dict(overlay), dict(overlay)
        test = expr.test
        if isinstance(test, ast.Compare) and len(test.ops) == 1 and \
                isinstance(test.ops[0], ast.Eq) and \
                len(test.comparators) == 1:
            lhs, rhs = test.left, test.comparators[0]
            if isinstance(rhs, ast.Name) and isinstance(lhs, ast.Constant):
                lhs, rhs = rhs, lhs
            if isinstance(lhs, ast.Name) and \
                    isinstance(rhs, ast.Constant) and \
                    isinstance(rhs.value, str):
                cur = env.lookup(lhs.id, overlay, seen)
                body_overlay[lhs.id] = {rhs.value}
                if cur is not None:
                    orelse_overlay[lhs.id] = cur - {rhs.value}
        body = eval_str_set(expr.body, env, body_overlay, seen)
        orelse = eval_str_set(expr.orelse, env, orelse_overlay, seen)
        if body is None or orelse is None:
            return None
        out = body | orelse
        return out if len(out) <= _MAX_SET else None
    if isinstance(expr, ast.JoinedStr):
        parts: List[Set[str]] = []
        for value in expr.values:
            if isinstance(value, ast.Constant):
                parts.append({str(value.value)})
                continue
            if isinstance(value, ast.FormattedValue):
                sub = eval_str_set(value.value, env, overlay, seen)
                if sub is None:
                    return None
                parts.append(sub)
                continue
            return None
        out = {""}
        for part in parts:
            out = {a + b for a in out for b in part}
            if len(out) > _MAX_SET:
                return None
        return out
    return None


def eval_str_prefixes(expr: ast.AST, env: _Env) -> Set[str]:
    """When full evaluation fails, the bounded literal PREFIXES of
    `expr` (e.g. ``"quality." + name`` -> {"quality."}) — recorded as
    wildcard producers so dynamic-name surfaces stay modeled."""
    full = eval_str_set(expr, env)
    if full is not None:
        return set()
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        left = eval_str_set(expr.left, env)
        if left is not None:
            return set(left)
        return eval_str_prefixes(expr.left, env)
    if isinstance(expr, ast.JoinedStr) and expr.values:
        head = expr.values[0]
        if isinstance(head, ast.Constant):
            return {str(head.value)}
        if isinstance(head, ast.FormattedValue):
            sub = eval_str_set(head.value, env)
            if sub is not None:
                return set(sub)
    return set()


# ---------------------------------------------------------------------------
# the model
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FamilyProd:
    sites: List[Site] = dataclasses.field(default_factory=list)
    #: distinct non-empty label-key sets observed across producer sites
    label_sets: Set[FrozenSet[str]] = dataclasses.field(default_factory=set)
    unlabeled: bool = False           # an unlabeled aggregate add exists
    unknown_labels: bool = False      # an unresolvable add: assume both


@dataclasses.dataclass
class SeriesProd:
    sites: List[Site] = dataclasses.field(default_factory=list)
    bare: bool = False                # recorded without a label
    labeled: bool = False             # recorded with a label


@dataclasses.dataclass
class ObsModel:
    """Every producer and consumer of a string-keyed telemetry/config
    name, project-wide.  Built once per lint invocation and shared via
    ``project.cache[CACHE_KEY]`` (schemadump and benchdiff reuse it)."""

    # producers
    metrics: Dict[str, Dict[str, List[Site]]] = \
        dataclasses.field(default_factory=dict)   # name -> kind -> sites
    metric_prefixes: Set[str] = dataclasses.field(default_factory=set)
    families: Dict[str, FamilyProd] = dataclasses.field(default_factory=dict)
    family_prefixes: Set[str] = dataclasses.field(default_factory=set)
    timeline: Dict[str, SeriesProd] = dataclasses.field(default_factory=dict)
    flight_kinds: Dict[str, List[Site]] = \
        dataclasses.field(default_factory=dict)
    ctl_rules: Dict[str, List[Site]] = dataclasses.field(default_factory=dict)
    routes: Dict[str, Site] = dataclasses.field(default_factory=dict)
    param_specs: Dict[str, Site] = dataclasses.field(default_factory=dict)
    actuations: Dict[str, Tuple[str, Site]] = \
        dataclasses.field(default_factory=dict)   # name -> (scope, site)
    verdicts_returned: Dict[str, Site] = \
        dataclasses.field(default_factory=dict)
    verdict_registry: Dict[str, Site] = \
        dataclasses.field(default_factory=dict)

    # consumers
    timeline_reads: List[Tuple[str, Site]] = \
        dataclasses.field(default_factory=list)
    metric_reads: List[Tuple[str, str, Site]] = \
        dataclasses.field(default_factory=list)   # (name, kind, site)
    expected_routes: Dict[str, Site] = dataclasses.field(default_factory=dict)
    param_uses: List[Tuple[str, Site]] = \
        dataclasses.field(default_factory=list)
    ini_reads: List[Tuple[str, str, Site]] = \
        dataclasses.field(default_factory=list)   # (section, key, site)
    benchdiff_paths: List[Tuple[str, Site]] = \
        dataclasses.field(default_factory=list)
    doc_rows: Dict[str, int] = dataclasses.field(default_factory=dict)
    doc_mentions: Set[str] = dataclasses.field(default_factory=set)
    has_doc: bool = False
    #: docs/tests/tools text for the GL1002 "documented anywhere" check
    corpus: str = ""
    has_corpus: bool = False
    #: identifier-ish string constants from bench.py + the project —
    #: the bench-artifact segment vocabulary benchdiff validates against
    bench_vocab: Set[str] = dataclasses.field(default_factory=set)
    has_bench_vocab: bool = False

    # ------------------------------------------------------------ queries

    def add_metric(self, name: str, kind: str, site: Site) -> None:
        self.metrics.setdefault(name, {}).setdefault(kind, []).append(site)

    def metric_kinds(self, name: str) -> Set[str]:
        return set(self.metrics.get(name, ()))

    def bare_series(self) -> Set[str]:
        """Every timeline key a consumer may read WITHOUT a label part:
        direct bare records, counter/histogram derivations, gauges, and
        families carrying an unlabeled aggregate sample."""
        out: Set[str] = set()
        for name, prod in self.timeline.items():
            if prod.bare:
                out.add(name)
        for name, kinds in self.metrics.items():
            if "counter" in kinds:
                out.add(name + ".rate")
            if "gauge" in kinds:
                out.add(name)
            if "histogram" in kinds:
                out.update((name + ".p50_ms", name + ".p99_ms",
                            name + ".rate"))
        for name, fam in self.families.items():
            if fam.unlabeled or fam.unknown_labels:
                out.add(name)
        return out

    def labeled_only_series(self) -> Set[str]:
        """Names published ONLY under a label — a bare read of one of
        these can never see a point (the GL1003 consumer direction)."""
        out: Set[str] = set()
        for name, fam in self.families.items():
            if fam.label_sets and not fam.unlabeled \
                    and not fam.unknown_labels:
                out.add(name)
        for name, prod in self.timeline.items():
            if prod.labeled and not prod.bare:
                out.add(name)
        return out - self.bare_series()

    def matches_prefix(self, name: str) -> bool:
        return any(name.startswith(p)
                   for p in (self.metric_prefixes | self.family_prefixes)
                   if p)

    def all_published(self) -> Dict[str, List[Site]]:
        """Producer name -> sites across every plane (GL1002 surface)."""
        out: Dict[str, List[Site]] = {}
        for name, kinds in self.metrics.items():
            for sites in kinds.values():
                out.setdefault(name, []).extend(sites)
        for name, fam in self.families.items():
            out.setdefault(name, []).extend(fam.sites)
        for name, prod in self.timeline.items():
            out.setdefault(name, []).extend(prod.sites)
        for name, sites in self.flight_kinds.items():
            out.setdefault(name, []).extend(sites)
        for name, sites in self.ctl_rules.items():
            out.setdefault(name, []).extend(sites)
        return out


# ---------------------------------------------------------------------------
# harvest
# ---------------------------------------------------------------------------

def _resolve_call(call: ast.Call, mod: ModuleInfo
                  ) -> Tuple[Optional[str], str]:
    """-> (full module path, function name) for `module.fn(...)` calls
    resolved through import aliases, or (None, bare-name) otherwise."""
    func = call.func
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return mod.resolve_head(func.value.id), func.attr
    if isinstance(func, ast.Name):
        target = mod.from_imports.get(func.id, "")
        if target:
            modpath, _, sym = target.rpartition(".")
            return modpath, sym
        return None, func.id
    return None, ""


def _arg(call: ast.Call, pos: int, kwname: str) -> Optional[ast.AST]:
    if len(call.args) > pos and not any(
            isinstance(a, ast.Starred) for a in call.args[:pos + 1]):
        return call.args[pos]
    for kw in call.keywords:
        if kw.arg == kwname:
            return kw.value
    return None


def _enclosing(mod: ModuleInfo, lineno: int) -> Tuple[str, Optional[ast.AST]]:
    best, best_line, node = "", -1, None
    for fn in mod.functions:
        end = getattr(fn.node, "end_lineno", fn.node.lineno)
        if fn.node.lineno <= lineno <= end and fn.node.lineno > best_line:
            best, best_line, node = fn.qualname, fn.node.lineno, fn.node
    return best, node


class _ModuleHarvest:
    """One pass over a module collecting every producer/consumer site."""

    _METRIC_PRODUCERS = {"counter": "counter", "inc": "counter",
                         "gauge": "gauge", "set_gauge": "gauge",
                         "histogram": "histogram", "observe": "histogram"}
    _METRIC_READS = {"counter_value": "counter", "gauge_value": "gauge",
                     "histogram_or_none": "histogram"}
    _TIMELINE_READS = {"latest", "window_values", "window_stats", "points"}
    _PARAM_USES = {"set_parameter", "get_param"}
    _ACTUATION_USES = {"clamp_actuation": 0, "actuation_spec": 0,
                       "actuate_index": 1, "bind_tier_knob": 0}

    def __init__(self, mod: ModuleInfo, model: ObsModel):
        self.mod = mod
        self.model = model
        self._envs: Dict[int, _Env] = {}
        #: Family-construct names per local variable, per function id —
        #: fam.add(value, {...}) label harvesting
        self._fam_vars: Dict[Tuple[int, str], Set[str]] = {}

    # ------------------------------------------------------------- helpers

    def _env_at(self, lineno: int) -> Tuple[str, _Env]:
        symbol, fn_node = _enclosing(self.mod, lineno)
        key = id(fn_node)
        if key not in self._envs:
            self._envs[key] = _Env(self.mod, fn_node)
        return symbol, self._envs[key]

    def _site(self, node: ast.AST, symbol: str) -> Site:
        return Site(self.mod.relpath, node.lineno, symbol)

    def _names_or_prefixes(self, expr: ast.AST, env: _Env
                           ) -> Tuple[Set[str], Set[str]]:
        vals = eval_str_set(expr, env)
        if vals is not None:
            return vals, set()
        return set(), eval_str_prefixes(expr, env)

    # ------------------------------------------------------------- harvest

    def run(self) -> None:
        self._harvest_routes_and_expected()
        for node in ast.walk(self.mod.tree):
            if isinstance(node, ast.Call):
                self._harvest_call(node)
            elif isinstance(node, ast.Return):
                self._harvest_verdict_return(node)

    def _harvest_call(self, call: ast.Call) -> None:
        modpath, fn = _resolve_call(call, self.mod)
        symbol, env = self._env_at(call.lineno)
        site = self._site(call, symbol)

        if modpath == _METRICS_MODULE or (
                modpath is None and fn == "Family"):
            self._harvest_metrics_call(call, fn, env, site)
        if modpath == _TRACE_MODULE and fn in ("span", "record"):
            self._harvest_named(call, _arg(call, 0, "name"), env,
                                "histogram", site)
        if modpath == _TIMELINE_MODULE:
            if fn == "record":
                self._harvest_timeline_record(call, env, site)
            elif fn in self._TIMELINE_READS:
                arg = _arg(call, 0, "name")
                if arg is not None:
                    vals = eval_str_set(arg, env)
                    for v in sorted(vals or ()):
                        self.model.timeline_reads.append((v, site))
        if modpath == _FLIGHT_MODULE and fn in ("record", "span"):
            arg = _arg(call, 1, "kind")
            if arg is not None:
                vals = eval_str_set(arg, env)
                for v in sorted(vals or ()):
                    self.model.flight_kinds.setdefault(v, []).append(site)
        if modpath == _CTLAUDIT_MODULE and fn == "record":
            arg = _arg(call, 0, "rule")
            if arg is not None:
                vals = eval_str_set(arg, env)
                for v in sorted(vals or ()):
                    self.model.ctl_rules.setdefault(v, []).append(site)
        if modpath == _QUALMON_MODULE and fn in ("gauge", "inc"):
            arg = _arg(call, 0, "name")
            if arg is not None:
                vals, prefixes = self._names_or_prefixes(arg, env)
                for v in sorted(vals):
                    fam = self.model.families.setdefault(
                        "quality." + v, FamilyProd())
                    fam.sites.append(site)
                    fam.unknown_labels = True
                for p in prefixes:
                    self.model.family_prefixes.add("quality." + p)
        if fn == "_spec" or fn == "ParamSpec":
            arg = _arg(call, 3, "name")
            if arg is not None:
                for v in sorted(eval_str_set(arg, env) or ()):
                    self.model.param_specs.setdefault(v, site)
        if fn == "ActuationSpec":
            arg = _arg(call, 0, "name")
            scope_arg = _arg(call, 4, "scope")
            scope = "index"
            if isinstance(scope_arg, ast.Constant) and \
                    isinstance(scope_arg.value, str):
                scope = scope_arg.value
            if arg is not None:
                for v in sorted(eval_str_set(arg, env) or ()):
                    self.model.actuations.setdefault(v, (scope, site))
        if fn == "_Objective":
            series_arg = _arg(call, 1, "series")
            if isinstance(series_arg, (ast.List, ast.Tuple)):
                for elt in series_arg.elts:
                    for v in sorted(eval_str_set(elt, env) or ()):
                        self.model.timeline_reads.append((v, site))
        if fn in self._PARAM_USES and isinstance(call.func, ast.Attribute):
            arg = _arg(call, 0, "name")
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                self.model.param_uses.append((arg.value, site))
        if fn in self._ACTUATION_USES and (
                modpath == _PARAMS_MODULE
                or isinstance(call.func, ast.Attribute)):
            pos = self._ACTUATION_USES[fn]
            arg = _arg(call, pos, "name" if pos == 0 else "knob")
            if fn == "bind_tier_knob":
                arg = _arg(call, 0, "knob")
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                self.model.param_uses.append((arg.value, site))
        if fn == "get_parameter" and isinstance(call.func, ast.Attribute) \
                and len(call.args) >= 2:
            sec, key = call.args[0], call.args[1]
            if isinstance(sec, ast.Constant) and isinstance(sec.value, str) \
                    and isinstance(key, ast.Constant) \
                    and isinstance(key.value, str):
                self.model.ini_reads.append((sec.value, key.value, site))
        if fn == "Metric" and self.mod.relpath.endswith("benchdiff.py"):
            arg = _arg(call, 0, "path")
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                self.model.benchdiff_paths.append((arg.value, site))
        if modpath == _QUALMON_MODULE and fn == "record_sample":
            arg = _arg(call, 5, "verdict")
            if arg is not None:
                for v in sorted(eval_str_set(arg, env) or ()):
                    if v:
                        self.model.verdicts_returned.setdefault(v, site)

    def _harvest_metrics_call(self, call: ast.Call, fn: str, env: _Env,
                              site: Site) -> None:
        if fn in self._METRIC_PRODUCERS:
            self._harvest_named(call, _arg(call, 0, "name"), env,
                                self._METRIC_PRODUCERS[fn], site)
        elif fn in self._METRIC_READS:
            arg = _arg(call, 0, "name")
            if arg is not None:
                vals = eval_str_set(arg, env)
                for v in sorted(vals or ()):
                    self.model.metric_reads.append(
                        (v, self._METRIC_READS[fn], site))
        elif fn == "Family":
            self._harvest_family(call, env, site)

    def _harvest_named(self, call: ast.Call, arg: Optional[ast.AST],
                       env: _Env, kind: str, site: Site) -> None:
        if arg is None:
            return
        vals, prefixes = self._names_or_prefixes(arg, env)
        for v in sorted(vals):
            self.model.add_metric(v, kind, site)
        self.model.metric_prefixes.update(prefixes)

    def _harvest_timeline_record(self, call: ast.Call, env: _Env,
                                 site: Site) -> None:
        arg = _arg(call, 0, "name")
        if arg is None:
            return
        label = _arg(call, 2, "label")
        labeled = label is not None and not (
            isinstance(label, ast.Constant) and label.value in ("", None))
        vals, prefixes = self._names_or_prefixes(arg, env)
        for v in sorted(vals):
            prod = self.model.timeline.setdefault(v, SeriesProd())
            prod.sites.append(site)
            if labeled:
                prod.labeled = True
            else:
                prod.bare = True
        self.model.metric_prefixes.update(prefixes)

    # -- families ----------------------------------------------------------

    def _harvest_family(self, call: ast.Call, env: _Env,
                        site: Site) -> None:
        arg = _arg(call, 0, "name")
        if arg is None:
            return
        names, prefixes = self._names_or_prefixes(arg, env)
        self.model.family_prefixes.update(prefixes)
        if not names:
            return
        unlabeled, label_sets, unknown = self._family_adds(call, env)
        for name in sorted(names):
            fam = self.model.families.setdefault(name, FamilyProd())
            fam.sites.append(site)
            fam.unlabeled |= unlabeled
            fam.unknown_labels |= unknown
            fam.label_sets |= label_sets

    def _family_adds(self, fam_call: ast.Call, env: _Env
                     ) -> Tuple[bool, Set[FrozenSet[str]], bool]:
        """Inspect every ``.add(value, labels)`` reaching this Family
        construction: chained directly, or through the local variable
        it is assigned to within the enclosing function."""
        _symbol, fn_node = _enclosing(self.mod, fam_call.lineno)
        scope: ast.AST = fn_node if fn_node is not None else self.mod.tree
        var_names: Set[str] = set()
        add_calls: List[ast.Call] = []
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign) and node.value is fam_call:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        var_names.add(tgt.id)
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "add":
                recv = node.func.value
                if recv is fam_call:
                    add_calls.append(node)
                elif isinstance(recv, ast.Call) and recv is fam_call:
                    add_calls.append(node)
        # second walk: adds through the assigned variable(s)
        if var_names:
            for node in ast.walk(scope):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "add" and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id in var_names:
                    add_calls.append(node)
        unlabeled, unknown = False, False
        label_sets: Set[FrozenSet[str]] = set()
        for add in add_calls:
            labels = _arg(add, 1, "labels")
            got = self._label_keys(labels, env)
            if got == "unlabeled":
                unlabeled = True
            elif got == "unknown":
                unknown = True
            elif got == "both":
                unlabeled = unknown = True
            else:
                label_sets.add(got)
        if not add_calls:
            unknown = True            # samples= kwarg or external fill
        return unlabeled, label_sets, unknown

    def _label_keys(self, labels: Optional[ast.AST], env: _Env):
        """-> frozenset of label keys, "unlabeled", "both" (conditional
        labels like ``{...} if mode else None``), or "unknown"."""
        if labels is None or (isinstance(labels, ast.Constant)
                              and labels.value is None):
            return "unlabeled"
        if isinstance(labels, ast.IfExp):
            arms = [self._label_keys(labels.body, env),
                    self._label_keys(labels.orelse, env)]
            if "unknown" in arms:
                return "unknown"
            if "unlabeled" in arms or "both" in arms:
                return "both"
            return arms[0]            # two labeled arms: report the first
        if isinstance(labels, ast.Name):
            exprs = env.assigns.get(labels.id, ())
            dicts = [e for e in exprs if isinstance(e, ast.Dict)]
            if len(dicts) == 1:
                return self._label_keys(dicts[0], env)
            return "unknown"
        if isinstance(labels, ast.Dict):
            keys: Set[str] = set()
            for k in labels.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
                else:
                    return "unknown"
            return frozenset(keys) if keys else "unlabeled"
        return "unknown"

    # -- routes / EXPECTED_ROUTES -----------------------------------------

    def _harvest_routes_and_expected(self) -> None:
        for node in ast.walk(self.mod.tree):
            # `self._routes: Dict[str, _Route] = {...}` is an AnnAssign
            if isinstance(node, ast.AnnAssign) and node.value is not None:
                tgt, value = node.target, node.value
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, value = node.targets[0], node.value
            else:
                continue
            tgt_name = tgt.id if isinstance(tgt, ast.Name) else (
                tgt.attr if isinstance(tgt, ast.Attribute) else "")
            symbol, _env = self._env_at(node.lineno)
            if tgt_name == "_routes" and isinstance(value, ast.Dict):
                for k in value.keys:
                    if isinstance(k, ast.Constant) and \
                            isinstance(k.value, str):
                        self.model.routes.setdefault(
                            k.value, self._site(k, symbol))
            if tgt_name == "EXPECTED_ROUTES" and \
                    isinstance(value, (ast.List, ast.Tuple)):
                for elt in value.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, str):
                        self.model.expected_routes.setdefault(
                            elt.value, self._site(elt, symbol))
            if tgt_name == "TRIAGE_VERDICTS" and \
                    isinstance(value, (ast.List, ast.Tuple)):
                for elt in value.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, str):
                        self.model.verdict_registry.setdefault(
                            elt.value, self._site(elt, symbol))

    # -- verdict classifier returns ---------------------------------------

    def _harvest_verdict_return(self, node: ast.Return) -> None:
        if _QUALMON_MODULE.split(".")[-1] not in self.mod.relpath and \
                not self.mod.relpath.endswith("qualmon.py"):
            return
        symbol, _fn = _enclosing(self.mod, node.lineno)
        if not symbol.startswith("classify_"):
            return
        val = node.value
        if isinstance(val, ast.Tuple) and val.elts:
            head = val.elts[0]
            if isinstance(head, ast.Constant) and \
                    isinstance(head.value, str):
                self.model.verdicts_returned.setdefault(
                    head.value, self._site(head, symbol))


# ---------------------------------------------------------------------------
# cross-tree surfaces
# ---------------------------------------------------------------------------

def _read_surface(project: Project, relpath: str) -> Optional[str]:
    """A cross-tree file's text: a planted in-memory extra source first,
    else the real file under the project's disk root."""
    if relpath in project.extra_sources:
        return project.extra_sources[relpath]
    if project.source_root:
        full = os.path.join(project.source_root, relpath)
        if os.path.isfile(full):
            with open(full, encoding="utf-8") as f:
                return f.read()
    return None


def _harvest_external_module(project: Project, model: ObsModel,
                             relpath: str) -> None:
    text = _read_surface(project, relpath)
    if text is None:
        return
    try:
        mod = ModuleInfo(relpath, text)
    except SyntaxError:
        return
    _ModuleHarvest(mod, model).run()


def _harvest_doc(project: Project, model: ObsModel) -> None:
    text = _read_surface(project, "docs/PARAMETERS.md")
    if text is None:
        return
    model.has_doc = True
    for lineno, line in enumerate(text.splitlines(), 1):
        stripped = line.strip()
        names = _BACKTICK.findall(stripped)
        for name in names:
            if _IDENTISH.match(name):
                model.doc_mentions.add(name)
        if stripped.startswith("|"):
            cells = [c.strip() for c in stripped.strip("|").split("|")]
            if cells and cells[0].startswith("`"):
                for name in _BACKTICK.findall(cells[0]):
                    if _IDENTISH.match(name) and \
                            name not in model.doc_rows:
                        model.doc_rows[name] = lineno


def _harvest_corpus(project: Project, model: ObsModel) -> None:
    """docs/tests/tools text, for the GL1002 "mentioned anywhere"
    check.  The producing package itself is deliberately excluded —
    a name trivially appears at its own call site."""
    chunks: List[str] = [text for path, text in
                         sorted(project.extra_sources.items())]
    root = project.source_root
    if root:
        for sub in ("docs", "tests", "tools"):
            base = os.path.join(root, sub)
            if not os.path.isdir(base):
                continue
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fname in sorted(filenames):
                    if fname.endswith((".py", ".md", ".sh", ".toml")):
                        try:
                            with open(os.path.join(dirpath, fname),
                                      encoding="utf-8") as f:
                                chunks.append(f.read())
                        except OSError:
                            continue
        for fname in ("bench.py", "README.md", "ROADMAP.md", "CHANGES.md"):
            full = os.path.join(root, fname)
            if os.path.isfile(full):
                with open(full, encoding="utf-8") as f:
                    chunks.append(f.read())
        model.has_corpus = True
    elif project.extra_sources:
        model.has_corpus = True
    model.corpus = "\n".join(chunks)


def _harvest_bench_vocab(project: Project, model: ObsModel) -> None:
    """Identifier-ish string constants from bench.py plus the project —
    every dotted segment of a benchdiff catalog path must appear here.

    The vocabulary is only trustworthy when the WHOLE package was
    parsed (artifact keys originate anywhere in it — e.g. `pct_peak`
    in utils/roofline.py); a subpackage-scoped lint of a disk tree
    would see a partial vocabulary and report phantom GL1001s, so it
    leaves `has_bench_vocab` unset and the benchdiff check silent.
    In-memory fixture projects are exempt: they are self-contained."""
    complete = project.source_root is None or any(
        p.endswith("utils/metrics.py") for p in project.modules)
    trees: List[ast.AST] = [m.tree for m in project.modules.values()]
    text = _read_surface(project, "bench.py")
    if text is not None and complete:
        try:
            trees.append(ast.parse(text))
            model.has_bench_vocab = True
        except SyntaxError:
            pass
    for tree in trees:
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    0 < len(node.value) <= 80:
                val = node.value
                if _IDENTISH.match(val):
                    model.bench_vocab.add(val)
                    for seg in val.split("."):
                        if seg:
                            model.bench_vocab.add(seg)


# ---------------------------------------------------------------------------
# build + checks
# ---------------------------------------------------------------------------

def build_model(project: Project) -> ObsModel:
    cached = project.cache.get(CACHE_KEY)
    if isinstance(cached, ObsModel):
        return cached
    model = ObsModel()
    for mod in project.modules.values():
        _ModuleHarvest(mod, model).run()
    # cross-tree consumer surfaces (disk-backed projects only, unless a
    # fixture plants them): the route-contract test's EXPECTED_ROUTES,
    # benchdiff's catalog, the docs, and the GL1002 corpus
    if not any(p.endswith("tests/test_hostprof.py")
               for p in project.modules):
        _harvest_external_module(project, model, "tests/test_hostprof.py")
    if not any(p.endswith("benchdiff.py") for p in project.modules):
        _harvest_external_module(project, model, "tools/benchdiff.py")
    _harvest_doc(project, model)
    _harvest_corpus(project, model)
    _harvest_bench_vocab(project, model)
    project.cache[CACHE_KEY] = model
    return model


def _consumed_names(model: ObsModel) -> Set[str]:
    """Every producer name a structured consumer resolves to, with
    timeline derivations folded back onto their base metric."""
    out: Set[str] = set()
    for name, _site in model.timeline_reads:
        out.add(name)
        for suffix in (".rate", ".p50_ms", ".p99_ms"):
            if name.endswith(suffix):
                out.add(name[: -len(suffix)])
    for name, _kind, _site in model.metric_reads:
        out.add(name)
    return out


def _check_series_reads(model: ObsModel) -> List[Finding]:
    out: List[Finding] = []
    bare = model.bare_series()
    labeled_only = model.labeled_only_series()
    seen: Set[Tuple[str, str, int]] = set()
    for name, site in model.timeline_reads:
        key = (name, site.path, site.line)
        if key in seen:
            continue
        seen.add(key)
        if name in bare or model.matches_prefix(name):
            continue
        if name in labeled_only:
            out.append(Finding(
                "GL1003", site.path, site.line,
                f"timeline read of bare series `{name}` but every "
                "producer publishes it labeled — the unlabeled key "
                "never receives a point (publish an unlabeled "
                "aggregate sample or read the labeled key)",
                site.symbol))
            continue
        out.append(Finding(
            "GL1001", site.path, site.line,
            f"timeline series `{name}` is consumed here but no "
            "producer publishes it (no matching timeline.record, "
            "counter/gauge/histogram derivation, or family sample)",
            site.symbol))
    for name, kind, site in model.metric_reads:
        kinds = model.metric_kinds(name)
        if kind in kinds or model.matches_prefix(name):
            continue
        if kinds:
            out.append(Finding(
                "GL1001", site.path, site.line,
                f"metric `{name}` is read as a {kind} but only "
                f"published as {'/'.join(sorted(kinds))} — the read "
                "resolves to a different instrument", site.symbol))
        else:
            out.append(Finding(
                "GL1001", site.path, site.line,
                f"metric `{name}` is read here but never published "
                "by any registry producer", site.symbol))
    return out


def _check_family_labels(model: ObsModel) -> List[Finding]:
    out: List[Finding] = []
    for name, fam in sorted(model.families.items()):
        if len(fam.label_sets) > 1:
            sets = " vs ".join(
                "{%s}" % ",".join(sorted(s))
                for s in sorted(fam.label_sets, key=sorted))
            site = fam.sites[0]
            out.append(Finding(
                "GL1003", site.path, site.line,
                f"family `{name}` is published with conflicting "
                f"label-key sets ({sets}) — consumers keying on one "
                "set silently miss samples from the other",
                site.symbol))
    return out


def _mentioned(name: str, corpus: str) -> bool:
    """Does the corpus mention `name` — either verbatim or in its
    Prometheus-rendered form (tests scrape /metrics, where `x.y` is
    exposed as `sptag_tpu_x_y`; see utils/metrics._metric_name)?"""
    if name in corpus:
        return True
    prom = "sptag_tpu_" + re.sub(r"[^0-9A-Za-z_]", "_", name)
    return prom in corpus


def _check_unconsumed(model: ObsModel) -> List[Finding]:
    if not model.has_corpus:
        corpus = ""
    else:
        corpus = model.corpus
    consumed = _consumed_names(model)
    out: List[Finding] = []
    for name, sites in sorted(model.all_published().items()):
        if name in consumed:
            continue
        if corpus and _mentioned(name, corpus):
            continue
        site = sites[0]
        out.append(Finding(
            "GL1002", site.path, site.line,
            f"`{name}` is published but never consumed by a "
            "structured reader and never mentioned in docs/tests/"
            "tools — document it, consume it, or justify it in the "
            "baseline", site.symbol))
    for name, site in sorted(model.verdict_registry.items()):
        if name not in model.verdicts_returned:
            out.append(Finding(
                "GL1002", site.path, site.line,
                f"triage verdict `{name}` is declared in "
                "TRIAGE_VERDICTS but no classifier returns it",
                site.symbol))
    return out


def _check_verdicts(model: ObsModel) -> List[Finding]:
    if not model.verdict_registry:
        return []
    out: List[Finding] = []
    for name, site in sorted(model.verdicts_returned.items()):
        if name not in model.verdict_registry:
            out.append(Finding(
                "GL1001", site.path, site.line,
                f"triage verdict `{name}` is produced here but absent "
                "from qualmon.TRIAGE_VERDICTS — dashboards and tests "
                "keying on the registry never see it", site.symbol))
    return out


def _check_docs(model: ObsModel) -> List[Finding]:
    if not model.has_doc:
        return []
    out: List[Finding] = []
    for name, site in sorted(model.param_specs.items()):
        if name not in model.doc_mentions:
            out.append(Finding(
                "GL1004", site.path, site.line,
                f"param spec `{name}` has no docs/PARAMETERS.md row",
                site.symbol))
    for name, (scope, site) in sorted(model.actuations.items()):
        if name not in model.doc_mentions:
            out.append(Finding(
                "GL1004", site.path, site.line,
                f"live actuation `{name}` ({scope}-scoped) has no "
                "docs/PARAMETERS.md row", site.symbol))
    known = set(model.param_specs) | set(model.actuations)
    ini_keys = {key for _sec, key, _site in model.ini_reads}
    for name, lineno in sorted(model.doc_rows.items()):
        if name not in known and name not in ini_keys:
            out.append(Finding(
                "GL1004", "docs/PARAMETERS.md", lineno,
                f"documented row `{name}` names no param spec, live "
                "actuation, or parsed INI key — stale doc row"))
    doc_sections = {"Service", "Aggregator", "QueryConfig"}
    seen: Set[str] = set()
    for sec, key, site in sorted(model.ini_reads):
        if sec not in doc_sections or key in seen:
            continue
        seen.add(key)
        if key not in model.doc_mentions:
            out.append(Finding(
                "GL1004", site.path, site.line,
                f"INI key [{sec}] {key} is parsed here but "
                "docs/PARAMETERS.md never documents it", site.symbol))
    return out


def _check_param_uses(model: ObsModel) -> List[Finding]:
    if not model.param_specs and not model.actuations:
        return []
    known = {n.lower() for n in model.param_specs}
    known |= {n.lower() for n in model.actuations}
    out: List[Finding] = []
    seen: Set[Tuple[str, str, int]] = set()
    for name, site in model.param_uses:
        key = (name, site.path, site.line)
        if key in seen:
            continue
        seen.add(key)
        if name.lower() not in known:
            out.append(Finding(
                "GL1005", site.path, site.line,
                f"param name `{name}` has no backing ParamSpec or "
                "live-actuation entry — set_parameter would reject it "
                "(or silently no-op)", site.symbol))
    for name, (scope, site) in sorted(model.actuations.items()):
        if scope == "index" and \
                name.lower() not in {n.lower()
                                     for n in model.param_specs}:
            out.append(Finding(
                "GL1005", site.path, site.line,
                f"index-scoped live actuation `{name}` matches no "
                "ParamSpec — actuate_index would raise at apply time",
                site.symbol))
    return out


def _check_routes(model: ObsModel) -> List[Finding]:
    if not model.routes or not model.expected_routes:
        return []
    out: List[Finding] = []
    for path, site in sorted(model.routes.items()):
        if path not in model.expected_routes:
            out.append(Finding(
                "GL1006", site.path, site.line,
                f"route `{path}` is registered but absent from "
                "EXPECTED_ROUTES — the route-contract tests skip it",
                site.symbol))
    for path, site in sorted(model.expected_routes.items()):
        if path not in model.routes:
            out.append(Finding(
                "GL1006", site.path, site.line,
                f"EXPECTED_ROUTES lists `{path}` but no handler "
                "registers it", site.symbol))
    return out


def _check_benchdiff(model: ObsModel) -> List[Finding]:
    if not model.benchdiff_paths or not model.has_bench_vocab:
        return []
    out: List[Finding] = []
    for path, site in model.benchdiff_paths:
        bad = unknown_catalog_segments(path, model.bench_vocab)
        if bad:
            out.append(Finding(
                "GL1001", site.path, site.line,
                f"benchdiff catalog metric `{path}` has segment(s) "
                f"{', '.join(repr(b) for b in bad)} that no bench.py "
                "artifact key produces — the diff would silently skip "
                "it", site.symbol))
    return out


def unknown_catalog_segments(path: str, vocab: Set[str]) -> List[str]:
    """The dotted segments of a benchdiff catalog path absent from the
    bench-artifact vocabulary (wildcard ``*`` segments are skipped).
    Shared with tools/benchdiff.py's startup validation."""
    return [seg for seg in path.split(".")
            if seg and seg != "*" and seg not in vocab]


def _covers_package(project: Project) -> bool:
    """The contract graph is a WHOLE-package analysis: producers and
    consumers live in different subpackages (slo.py reads series that
    qualmon publishes; docs rows name specs from core/params.py), so a
    subpackage-scoped lint of a disk tree would report phantom
    GL1001/1002/1004s for every cross-subpackage edge.  Disk-backed
    projects run the pass only when the anchor modules of both halves
    were parsed; in-memory fixture projects are self-contained and
    always run."""
    if project.source_root is None:
        return True
    has_metrics = any(p.endswith("utils/metrics.py")
                      for p in project.modules)
    has_params = any(p.endswith("core/params.py")
                     for p in project.modules)
    return has_metrics and has_params


def check(project: Project) -> List[Finding]:
    if not _covers_package(project):
        return []
    model = build_model(project)
    out: List[Finding] = []
    out.extend(_check_series_reads(model))
    out.extend(_check_family_labels(model))
    out.extend(_check_verdicts(model))
    out.extend(_check_unconsumed(model))
    out.extend(_check_docs(model))
    out.extend(_check_param_uses(model))
    out.extend(_check_routes(model))
    out.extend(_check_benchdiff(model))
    return out
