"""GL605 — cost-ledger coverage of device kernels.

The roofline-observability subsystem (ISSUE 6) only works if EVERY
device kernel has a registered analytic cost formula
(utils/costmodel.py): an unregistered kernel silently runs outside the
achieved-FLOP/s accounting, so "chip utilization" quietly regresses to
"chip utilization of the kernels someone remembered".  GL605 is the
static backstop:

* every jit root under ``algo/`` / ``ops/`` (decorated ``@jax.jit`` /
  ``functools.partial(jax.jit, ...)``, or a ``jax.jit(f)`` call site)
  must be the kernel argument of a ``costmodel.register(<family>,
  <kernel>, <formula>)`` call somewhere in the project;
* ``jax.jit(other_module.fn)`` dispatch sites are satisfied by a
  registration of ``fn`` in any module (the registry is project-wide);
* a ``costmodel.register`` whose family argument is not a string
  literal is flagged too — the ledger keys series off family names and
  never expires one (the GL6xx cardinality argument).

Escape hatch: a justified baseline entry (tools/graftlint/baseline.toml)
— for kernels that genuinely sit outside the roofline story (build-time
closures whose shapes never reach a perf report), with the justification
saying WHY.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from tools.graftlint.core import Finding, ModuleInfo, Project, _dotted

RULES = {
    "GL605": "device kernel has no cost-ledger entry — register an "
             "analytic FLOPs/bytes formula (utils/costmodel.py) or "
             "justify the exemption in the baseline",
}

_COSTMODEL_MODULE = "sptag_tpu.utils.costmodel"

#: path fragments that scope the rule: the device-kernel packages
#: (parallel/ joined in ISSUE 11 — the sharded/mesh kernels must feed
#: the roofline ledger like every single-chip kernel)
_SCOPED = ("algo/", "ops/", "parallel/")


def _is_register_call(call: ast.Call, mod: ModuleInfo) -> bool:
    func = call.func
    if isinstance(func, ast.Attribute) and isinstance(func.value, ast.Name):
        return (mod.resolve_head(func.value.id) == _COSTMODEL_MODULE
                and func.attr == "register")
    if isinstance(func, ast.Name):
        return mod.from_imports.get(func.id, "") == \
            _COSTMODEL_MODULE + ".register"
    return False


def _registered_names(project: Project) -> Set[str]:
    """Project-wide set of kernel function names bound to a ledger entry
    (the second argument of every costmodel.register call)."""
    out: Set[str] = set()
    for mod in project.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or \
                    not _is_register_call(node, mod):
                continue
            if len(node.args) >= 2:
                target = node.args[1]
                if isinstance(target, ast.Name):
                    out.add(target.id)
                elif isinstance(target, ast.Attribute):
                    out.add(target.attr)
    return out


def _is_jax_jit_func(node: ast.AST, mod: ModuleInfo) -> bool:
    d = _dotted(node)
    if d is None:
        return False
    head, _, rest = d.partition(".")
    full = mod.resolve_head(head)
    if full is not None:
        d = full + ("." + rest if rest else "")
    return d.endswith("jax.jit") or (
        d == "jit" and mod.from_imports.get("jit", "").endswith("jax.jit"))


def _in_scope(mod: ModuleInfo) -> bool:
    return any(frag in mod.relpath for frag in _SCOPED)


def _enclosing(mod: ModuleInfo, lineno: int) -> str:
    best, best_line = "", -1
    for fn in mod.functions:
        end = getattr(fn.node, "end_lineno", fn.node.lineno)
        if fn.node.lineno <= lineno <= end and fn.node.lineno > best_line:
            best, best_line = fn.qualname, fn.node.lineno
    return best


def check(project: Project) -> List[Finding]:
    registered = _registered_names(project)
    out: List[Finding] = []
    for mod in project.modules.values():
        # register-call hygiene (part 3) applies EVERYWHERE the ledger
        # is fed from — the registry is project-wide and never expires a
        # family name
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or \
                    not _is_register_call(node, mod):
                continue
            fam = node.args[0] if node.args else None
            if fam is not None and not (
                    isinstance(fam, ast.Constant)
                    and isinstance(fam.value, str)):
                out.append(Finding(
                    "GL605", mod.relpath, node.lineno,
                    "costmodel.register family name is not a string "
                    "literal — the ledger never expires a family, so "
                    "dynamic names make its cardinality unbounded",
                    _enclosing(mod, node.lineno)))
        if not _in_scope(mod):
            # kernel-coverage checks (parts 1-2) scope to the device-
            # kernel packages only
            continue
        seen_lines: Set[int] = set()
        # 1) decorated jit roots must be registered by name
        for fn in mod.functions:
            if not fn.is_jit_root:
                continue
            if fn.name in registered:
                continue
            out.append(Finding(
                "GL605", mod.relpath, fn.line,
                f"jitted kernel `{fn.name}` has no cost-ledger entry — "
                "costmodel.register a FLOPs/bytes formula so it appears "
                "in roofline accounting (or baseline-justify it)",
                fn.qualname))
            seen_lines.add(fn.line)
        # 2) jax.jit(<imported fn>) dispatch sites: the target must be
        #    registered SOMEWHERE; local defs were covered above
        local_names = {fn.name for fn in mod.functions}
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if not _is_jax_jit_func(node.func, mod):
                continue
            target = node.args[0]
            name: Optional[str] = None
            if isinstance(target, ast.Attribute):
                name = target.attr
            elif isinstance(target, ast.Name) and \
                    target.id not in local_names:
                name = target.id
            if name is None or name in registered:
                continue
            if node.lineno in seen_lines:
                continue
            out.append(Finding(
                "GL605", mod.relpath, node.lineno,
                f"jax.jit dispatch of `{name}` has no cost-ledger entry "
                "— register it in its defining module (or baseline-"
                "justify it)", _enclosing(mod, node.lineno)))
            seen_lines.add(node.lineno)
    return out
