"""graftlint core — the shared AST project model every checker runs on.

The value proposition of sptag_tpu is that the search/build hot paths stay
on-device as a small number of compiled XLA programs (PAPER.md; TPU-KNN
arXiv:2206.14286 holds peak FLOP/s only while host<->device syncs and
recompilations stay out of the query loop).  Nothing in Python enforces
that — a stray `.item()`, a retrace on a Python-int shape, or an unlocked
cross-thread mutation lands silently and shows up rounds later as a bench
regression.  graftlint is the static backstop: an AST pass with
codebase-specific knowledge (which functions are jitted, which attributes
are lock-protected, which modules are error-code boundaries).

This module provides:

* `Project` — parse a file tree (or in-memory sources) into `ModuleInfo` /
  `FunctionInfo` records with import-alias tables and a call graph;
* jit-root detection (`@jax.jit`, `@functools.partial(jax.jit, ...)`,
  `jax.jit(f, ...)` call sites, `shard_map(f, ...)`) including
  `static_argnames` extraction, and transitive jit-REACHABILITY over the
  call graph (nested defs inside a jitted body are traced too);
* a single-pass local taint analysis marking names that hold traced jax
  values (`tracer_taint`), used by the host-sync checker;
* the `Finding` record and rule registry every checker reports through.

Checkers live in sibling modules (hostsync, retrace, concurrency,
errorpath, dtype_parity); `runner.py` wires them to the baseline and CLI.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

#: modules whose attributes produce traced values inside a jit region
JAX_VALUE_MODULES = {"jax.numpy", "jax.lax", "jax"}

#: alias heads treated as numpy (host) for the host-sync checker
NUMPY_MODULES = {"numpy"}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint hit.  `symbol` is the enclosing function qualname (or ""
    at module level) — baseline entries match on (rule, path, symbol) so
    unrelated line drift does not invalidate a suppression."""

    rule: str          # e.g. "GL101"
    path: str          # repo-relative posix path
    line: int          # 1-based
    message: str
    symbol: str = ""

    def format(self) -> str:
        where = f" [in {self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.rule} {self.message}{where}"


@dataclasses.dataclass
class FunctionInfo:
    node: ast.AST                     # FunctionDef / AsyncFunctionDef / Lambda
    qualname: str                     # module-relative, dotted
    module: "ModuleInfo"
    parent: Optional["FunctionInfo"]
    is_jit_root: bool = False
    is_shard_root: bool = False
    static_args: Set[str] = dataclasses.field(default_factory=set)
    jit_reachable: bool = False

    @property
    def name(self) -> str:
        return getattr(self.node, "name", "<lambda>")

    @property
    def line(self) -> int:
        return self.node.lineno

    def param_names(self) -> List[str]:
        a = self.node.args
        params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
        if a.vararg:
            params.append(a.vararg.arg)
        if a.kwarg:
            params.append(a.kwarg.arg)
        return params


class ModuleInfo:
    """One parsed source file: AST, import aliases, functions, classes."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.source = source
        self.tree = ast.parse(source, filename=self.relpath)
        # alias -> full module path, e.g. {"np": "numpy",
        # "jnp": "jax.numpy", "dist_ops": "sptag_tpu.ops.distance"}
        self.import_aliases: Dict[str, str] = {}
        # name -> "module.symbol" for from-imports of functions, e.g.
        # {"query_bucket": "sptag_tpu.utils.query_bucket"}
        self.from_imports: Dict[str, str] = {}
        self.functions: List[FunctionInfo] = []
        self._by_qualname: Dict[str, FunctionInfo] = {}
        self._collect_imports()
        self._collect_functions()

    # -------------------------------------------------------------- imports

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        self.import_aliases[alias.asname] = alias.name
                    else:
                        # `import a.b` binds the name `a` (to package a),
                        # NOT a.b — mapping 'a' -> 'a.b' would misresolve
                        # every other a.* reference in the module (a lazy
                        # `import jax.profiler` must not hijack `jax.jit`)
                        head = alias.name.split(".")[0]
                        self.import_aliases[head] = head
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.from_imports[alias.asname or alias.name] = \
                        f"{node.module}.{alias.name}"
                    # `from sptag_tpu.ops import distance as dist_ops`
                    # also registers a module alias
                    self.import_aliases.setdefault(
                        alias.asname or alias.name,
                        f"{node.module}.{alias.name}")

    def resolve_head(self, name: str) -> Optional[str]:
        """Map the head of a dotted reference to a full module path."""
        return self.import_aliases.get(name)

    # ------------------------------------------------------------ functions

    def _collect_functions(self) -> None:
        def visit(node: ast.AST, prefix: str, parent: Optional[FunctionInfo]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}" if prefix else child.name
                    info = FunctionInfo(child, qual, self, parent)
                    self.functions.append(info)
                    self._by_qualname[qual] = info
                    visit(child, qual + ".", info)
                elif isinstance(child, ast.ClassDef):
                    visit(child, (prefix or "") + child.name + ".", parent)
                else:
                    visit(child, prefix, parent)

        visit(self.tree, "", None)

    def function(self, qualname: str) -> Optional[FunctionInfo]:
        return self._by_qualname.get(qualname)

    def functions_named(self, name: str) -> List[FunctionInfo]:
        return [f for f in self.functions if f.name == name]

    def classes(self) -> List[ast.ClassDef]:
        return [n for n in ast.walk(self.tree)
                if isinstance(n, ast.ClassDef)]


# ---------------------------------------------------------------------------
# jit-root detection
# ---------------------------------------------------------------------------

def _dotted(node: ast.AST) -> Optional[str]:
    """`jax.numpy.sum` -> "jax.numpy.sum"; None for non-name chains."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jax_jit(node: ast.AST, mod: ModuleInfo) -> bool:
    d = _dotted(node)
    if d is None:
        return False
    head, _, rest = d.partition(".")
    full = mod.resolve_head(head)
    if full is not None:
        d = full + ("." + rest if rest else "")
    return d in ("jax.jit", "jax.jit.jit") or d.endswith("jax.jit") or \
        d == "jit" and mod.from_imports.get("jit", "").endswith("jax.jit")


def _is_shard_map(node: ast.AST, mod: ModuleInfo) -> bool:
    d = _dotted(node)
    if d is None:
        return False
    if d.split(".")[-1] != "shard_map":
        return False
    head = d.split(".")[0]
    full = mod.resolve_head(head) or head
    return full.startswith("jax") or d == "shard_map"


def _static_args_from_call(call: ast.Call) -> Set[object]:
    """Constants named in static_argnames (str) / static_argnums (int).
    Ints are positional indices — `_resolve_static` maps them to the
    owning function's parameter names."""
    out: Set[object] = set()
    for kw in call.keywords:
        if kw.arg in ("static_argnames", "static_argnums"):
            v = kw.value
            if isinstance(v, ast.Constant) and \
                    isinstance(v.value, (str, int)):
                out.add(v.value)
            elif isinstance(v, (ast.Tuple, ast.List, ast.Set)):
                out |= {e.value for e in v.elts
                        if isinstance(e, ast.Constant)
                        and isinstance(e.value, (str, int))}
    return out


def _resolve_static(fn: FunctionInfo, items: Set[object]) -> Set[str]:
    params = fn.param_names()
    names: Set[str] = set()
    for item in items:
        if isinstance(item, str):
            names.add(item)
        elif isinstance(item, int) and 0 <= item < len(params):
            names.add(params[item])
    return names


def _mark_jit_roots(mod: ModuleInfo) -> None:
    # decorator forms
    for fn in mod.functions:
        for dec in getattr(fn.node, "decorator_list", []):
            if _is_jax_jit(dec, mod):
                fn.is_jit_root = True
            elif isinstance(dec, ast.Call):
                if _is_jax_jit(dec.func, mod):
                    fn.is_jit_root = True
                    fn.static_args |= _resolve_static(
                        fn, _static_args_from_call(dec))
                elif _dotted(dec.func) in ("functools.partial", "partial") \
                        and dec.args and _is_jax_jit(dec.args[0], mod):
                    fn.is_jit_root = True
                    fn.static_args |= _resolve_static(
                        fn, _static_args_from_call(dec))
    # call forms: jax.jit(f, ...) / shard_map(f, ...) / pallas_call(f, ...)
    # anywhere in the module
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        target = node.args[0]
        if not isinstance(target, ast.Name):
            continue
        for fn in mod.functions_named(target.id):
            if _is_jax_jit(node.func, mod):
                fn.is_jit_root = True
                fn.static_args |= _resolve_static(
                    fn, _static_args_from_call(node))
            elif _is_shard_map(node.func, mod):
                fn.is_shard_root = True
            elif (_dotted(node.func) or "").split(".")[-1] == \
                    "pallas_call":
                # a Pallas kernel body is traced/compiled like a jit
                # root (device program; host syncs inside are fatal)
                fn.is_jit_root = True


# ---------------------------------------------------------------------------
# call graph + jit reachability
# ---------------------------------------------------------------------------

def _called_names(fn: FunctionInfo) -> List[Tuple[str, Optional[str]]]:
    """(simple_name, module_alias_or_None) for every call inside `fn`,
    excluding calls that belong to nested function bodies (those get their
    own FunctionInfo)."""
    out: List[Tuple[str, Optional[str]]] = []
    nested = {f.node for f in fn.module.functions
              if f.parent is fn}

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if child in nested:
                continue
            if isinstance(child, ast.Call):
                f = child.func
                if isinstance(f, ast.Name):
                    out.append((f.id, None))
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name):
                    out.append((f.attr, f.value.id))
                # also treat bare function references passed as args as
                # potential calls (lax.while_loop(cond, body, ...),
                # lax.map(body, xs), vmap(f)(..))
                for arg in child.args:
                    if isinstance(arg, ast.Name):
                        out.append((arg.id, None))
            visit(child)

    visit(fn.node)
    return out


class Project:
    """All parsed modules plus the cross-module function index."""

    def __init__(self, sources: Dict[str, str],
                 package_root: str = "sptag_tpu"):
        self.package_root = package_root
        self.modules: Dict[str, ModuleInfo] = {}
        self.errors: List[Finding] = []
        #: shared per-pass memo store: checkers that build expensive
        #: derived models (lock topology, guarded-by pass, the class
        #: attribute registry, the trace-contract call model) key them
        #: here so every registered pass shares ONE parse + call graph
        #: per lint invocation instead of rebuilding its own
        self.cache: Dict[str, object] = {}
        #: non-Python sources handed to the project (e.g. a planted
        #: "docs/PARAMETERS.md" in an obsgraph fixture) — checkers that
        #: cross-reference doc surfaces read them from here first, then
        #: fall back to `source_root` on disk
        self.extra_sources: Dict[str, str] = {}
        #: repo root when this project was parsed from a real tree
        #: (from_tree sets it); None for in-memory fixture projects —
        #: cross-tree surfaces (docs/, tests/, tools/) are only
        #: consulted when this is set
        self.source_root: Optional[str] = None
        for relpath, src in sorted(sources.items()):
            if not relpath.endswith(".py"):
                self.extra_sources[relpath.replace(os.sep, "/")] = src
                continue
            try:
                self.modules[relpath] = ModuleInfo(relpath, src)
            except SyntaxError as e:
                self.errors.append(Finding(
                    "GL000", relpath, e.lineno or 1,
                    f"syntax error: {e.msg}"))
        # module path ("sptag_tpu.ops.distance") -> ModuleInfo
        self.by_modpath: Dict[str, ModuleInfo] = {}
        for relpath, mod in self.modules.items():
            modpath = relpath[:-3].replace("/", ".")
            if modpath.endswith(".__init__"):
                modpath = modpath[: -len(".__init__")]
            self.by_modpath[modpath] = mod
        for mod in self.modules.values():
            _mark_jit_roots(mod)
        self._propagate_reachability()

    @classmethod
    def from_tree(cls, root: str,
                  package_root: str = "sptag_tpu") -> "Project":
        """Parse every .py file under `root`.  Paths in findings are
        CWD-relative when `root` sits under the current directory (so
        `graftlint sptag_tpu/core` from the repo root still reports
        `sptag_tpu/core/index.py`, matching baseline entries and the
        path-scoped checkers); otherwise they fall back to relative to
        the parent of `root`."""
        root = os.path.abspath(root.rstrip("/"))
        base = os.path.dirname(root)
        cwd_rel = os.path.relpath(root, os.getcwd())
        if not cwd_rel.startswith(os.pardir) and not os.path.isabs(cwd_rel):
            base = os.getcwd()
        sources: Dict[str, str] = {}
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fname)
                rel = os.path.relpath(full, base)
                with open(full, encoding="utf-8") as f:
                    sources[rel] = f.read()
        project = cls(sources, package_root=package_root)
        project.source_root = base
        return project

    # -------------------------------------------------------- reachability

    def _resolve_call(self, mod: ModuleInfo, name: str,
                      alias: Optional[str]) -> List[FunctionInfo]:
        if alias is None:
            # same module (any nesting level — simple-name resolution)
            local = mod.functions_named(name)
            if local:
                return local
            # from-import of a project function
            target = mod.from_imports.get(name)
            if target and target.startswith(self.package_root):
                modpath, _, sym = target.rpartition(".")
                tmod = self.by_modpath.get(modpath)
                if tmod:
                    return tmod.functions_named(sym)
            return []
        if alias == "self":
            # method call on the same class — approximate by name within
            # the module (method names are unique enough in practice)
            return mod.functions_named(name)
        full = mod.resolve_head(alias)
        if full and full.startswith(self.package_root):
            tmod = self.by_modpath.get(full)
            if tmod:
                return tmod.functions_named(name)
        return []

    def _propagate_reachability(self) -> None:
        queue: List[FunctionInfo] = []
        for mod in self.modules.values():
            for fn in mod.functions:
                if fn.is_jit_root or fn.is_shard_root:
                    fn.jit_reachable = True
                    queue.append(fn)
        seen: Set[int] = {id(f) for f in queue}
        while queue:
            fn = queue.pop()
            # nested defs inside a jitted body are traced with it
            for child in fn.module.functions:
                if child.parent is fn and id(child) not in seen:
                    child.jit_reachable = True
                    seen.add(id(child))
                    queue.append(child)
            for name, alias in _called_names(fn):
                for callee in self._resolve_call(fn.module, name, alias):
                    if id(callee) not in seen:
                        callee.jit_reachable = True
                        seen.add(id(callee))
                        queue.append(callee)

    def jit_reachable_functions(self) -> List[FunctionInfo]:
        return [fn for mod in self.modules.values()
                for fn in mod.functions if fn.jit_reachable]


# ---------------------------------------------------------------------------
# local taint analysis (traced-value tracking)
# ---------------------------------------------------------------------------

#: attribute accesses that yield STATIC (host) values even on a tracer
STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "itemsize"}


#: jax/jnp functions that return HOST values even under trace — metadata
#: queries, not array computations
_JAX_STATIC_FNS = {"issubdtype", "dtype", "result_type", "shape", "ndim",
                   "iinfo", "finfo", "can_cast", "promote_types", "size"}


def _is_jax_producing_call(call: ast.Call, mod: ModuleInfo) -> bool:
    """Does this call produce a traced jax value?  True for jnp.* / lax.* /
    jax.* attribute calls (resolved through the module's import aliases),
    excluding dtype/shape metadata queries which are trace-time static."""
    d = _dotted(call.func)
    if d is None:
        return False
    head, _, rest = d.partition(".")
    full = mod.resolve_head(head)
    if full is None:
        return False
    if d.split(".")[-1] in _JAX_STATIC_FNS:
        return False
    base = full.split(".")[0]
    return base == "jax"


def tracer_taint(fn: FunctionInfo,
                 inherited: Optional[Set[str]] = None) -> Set[str]:
    """Names in `fn` that (statically) hold traced jax values.

    Seeds: non-static parameters of a jit/shard ROOT (those are tracers by
    construction) and any name assigned from a jnp./lax./jax. call.  Taint
    propagates through arithmetic, comparisons, subscripts and calls that
    take a tainted argument; it is KILLED by `.shape` / `.dtype` / `.ndim`
    access and by `len()` / `np.*` (host) calls — shape-derived Python ints
    are static, not traced.  One forward pass, no fixpoint: good enough for
    straight-line kernel code, and a missed loop-carried taint only costs
    a false negative, never a false positive.
    """
    mod = fn.module
    tainted: Set[str] = set(inherited or ())
    if fn.is_jit_root or fn.is_shard_root:
        for p in fn.param_names():
            if p not in fn.static_args and p != "self":
                tainted.add(p)

    def expr_tainted(node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return expr_tainted(node.value)
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is not None:
                head = d.split(".")[0]
                full = mod.resolve_head(head)
                if full and full.split(".")[0] in NUMPY_MODULES:
                    return False          # host value (its own lint)
                if d.split(".")[-1] == "len" or head == "len":
                    return False
            if _is_jax_producing_call(node, mod):
                return True
            return any(expr_tainted(a) for a in node.args) or \
                any(expr_tainted(k.value) for k in node.keywords)
        if isinstance(node, ast.BinOp):
            return expr_tainted(node.left) or expr_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return expr_tainted(node.operand)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` is a structural host check,
            # decidable on a tracer without materializing it
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False
            return expr_tainted(node.left) or \
                any(expr_tainted(c) for c in node.comparators)
        if isinstance(node, ast.Subscript):
            return expr_tainted(node.value)
        if isinstance(node, ast.IfExp):
            return expr_tainted(node.body) or expr_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(expr_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return expr_tainted(node.value)
        return False

    nested = {f.node for f in mod.functions if f.parent is fn}

    def visit(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if child in nested:
                continue
            if isinstance(child, ast.Assign) and \
                    expr_tainted(child.value):
                for tgt in child.targets:
                    for n in ast.walk(tgt):
                        if isinstance(n, ast.Name):
                            tainted.add(n.id)
            elif isinstance(child, (ast.AugAssign, ast.AnnAssign)) and \
                    child.value is not None and expr_tainted(child.value):
                if isinstance(child.target, ast.Name):
                    tainted.add(child.target.id)
            visit(child)

    visit(fn.node)
    fn._taint_expr = expr_tainted          # checkers reuse the evaluator
    return tainted


def body_nodes(fn: FunctionInfo) -> Iterable[ast.AST]:
    """Walk `fn`'s body EXCLUDING nested function bodies (those are
    analyzed as their own FunctionInfo)."""
    nested = {f.node for f in fn.module.functions if f.parent is fn}

    def visit(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if child in nested:
                continue
            yield child
            yield from visit(child)

    yield from visit(fn.node)


def statements_under_with(fn: FunctionInfo,
                          ctx_names: Sequence[str]) -> Set[int]:
    """Line numbers of statements inside a `with <self.X>:` block where X
    is one of `ctx_names` — the concurrency checker's "lock held" set."""
    held: Set[int] = set()

    def visit(node: ast.AST, under: bool) -> None:
        for child in ast.iter_child_nodes(node):
            now = under
            if isinstance(child, (ast.With, ast.AsyncWith)):
                for item in child.items:
                    d = _dotted(item.context_expr)
                    if d is None and isinstance(item.context_expr, ast.Call):
                        d = _dotted(item.context_expr.func)
                    if d and d.split(".")[-1] in ctx_names:
                        now = True
            if now and hasattr(child, "lineno"):
                held.add(child.lineno)
            visit(child, now)

    visit(fn.node, False)
    return held
