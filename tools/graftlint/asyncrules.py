"""GL703 — sync/async hazard lint.

The serving tier mixes an asyncio event loop (serve/server.py,
serve/aggregator.py) with thread-based clients and device work.  Two
hazard families kill its tail latency:

* BLOCKING THE LOOP: a `threading.Lock` acquired — or blocking I/O /
  `time.sleep` / a device sync executed — inside an `async def` stalls
  EVERY connection the loop serves, not just the offending one.  The
  sanctioned escape is `run_in_executor` (whose nested sync callable is
  deliberately out of scope here: it runs on an executor thread).
* SERIALIZING UNDER AN asyncio.Lock: `await`ing anything other than the
  write/drain the lock exists to serialize (an RPC, a future, a gather)
  while holding an `asyncio.Lock` extends the critical section across an
  arbitrary suspension — one slow awaitable convoys every task behind
  the lock.  `await writer.drain()` (and `wait_for(...drain...)`) is the
  pattern serve/server.py's per-connection lock exists for; everything
  else is flagged.

Lock identities resolve through the shared project lock model
(tools/graftlint/lockgraph.LockModel), so a `threading.Lock` created in
`__init__` and acquired in an `async def` of the same class is caught
even without a name hint.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.graftlint import lockgraph
from tools.graftlint.core import Finding, FunctionInfo, Project, _dotted

RULES = {
    "GL703": "sync/async hazard: threading lock or blocking call on the "
             "event loop, or a non-write await under an asyncio.Lock",
}

#: awaits allowed while an asyncio.Lock is held — the write+flush the
#: lock serializes.  `wait_for` is unwrapped to its first argument.
_AWAIT_OK_LEAVES = {"write", "writelines", "drain", "close", "wait_closed",
                    "sendall"}


def _await_leaf(value: ast.AST) -> str:
    """Leaf name of an awaited expression, unwrapping wait_for."""
    if isinstance(value, ast.Call):
        d = _dotted(value.func)
        leaf = d.split(".")[-1] if d else (
            value.func.attr if isinstance(value.func, ast.Attribute)
            else "<call>")
        if leaf == "wait_for" and value.args:
            return _await_leaf(value.args[0])
        return leaf
    if isinstance(value, ast.Name):
        return value.id
    return "<expression>"


def _asyncio_lock_item(item: ast.withitem, fn: FunctionInfo,
                       model: lockgraph.LockModel) -> Optional[str]:
    """Display name when an `async with` item is (or smells like) an
    asyncio lock."""
    lock = model.resolve_lock_expr(fn, item.context_expr)
    if lock is not None:
        return lock.canonical if lock.kind in ("asyncio", "unknown") \
            else None
    d = _dotted(item.context_expr)
    if d and "lock" in d.split(".")[-1].lower():
        # unresolvable expression (tuple-unpacked local, dataclass field)
        # with a lock-ish name: an `async with` on it is an asyncio lock
        # by construction
        return d
    return None


def _scan_async_fn(fn: FunctionInfo,
                   model: lockgraph.LockModel) -> List[Finding]:
    out: List[Finding] = []
    mod = fn.module
    nested = {f.node for f in mod.functions if f.parent is fn}

    def visit(node: ast.AST, lock_held: Optional[str],
              in_await: bool) -> None:
        for child in ast.iter_child_nodes(node):
            if child in nested:
                continue
            now_lock = lock_held
            now_await = in_await
            if isinstance(child, ast.With):
                for item in child.items:
                    lock = model.resolve_lock_expr(fn, item.context_expr)
                    if lock is not None and lock.kind == "threading":
                        out.append(Finding(
                            "GL703", mod.relpath, child.lineno,
                            f"threading lock `{lock.canonical}` held "
                            "inside `async def` — a contended acquire "
                            "stalls the whole event loop (use "
                            "asyncio.Lock or run_in_executor)",
                            fn.qualname))
            elif isinstance(child, ast.AsyncWith):
                for item in child.items:
                    name = _asyncio_lock_item(item, fn, model)
                    if name is not None:
                        now_lock = name
            elif isinstance(child, ast.Await):
                now_await = True
                if lock_held is not None:
                    leaf = _await_leaf(child.value)
                    if leaf not in _AWAIT_OK_LEAVES:
                        out.append(Finding(
                            "GL703", mod.relpath, child.lineno,
                            f"`await {leaf}` while holding asyncio lock "
                            f"`{lock_held}` — the critical section spans "
                            "an arbitrary suspension and convoys every "
                            "task behind the lock", fn.qualname))
            elif isinstance(child, ast.Call):
                d = _dotted(child.func)
                if d and d.split(".")[-1] == "acquire":
                    recv = child.func
                    if isinstance(recv, ast.Attribute):
                        lock = model.resolve_lock_expr(fn, recv.value)
                        if lock is not None and lock.kind == "threading":
                            out.append(Finding(
                                "GL703", mod.relpath, child.lineno,
                                f"`{lock.canonical}.acquire()` inside "
                                "`async def` blocks the event loop",
                                fn.qualname))
                if not in_await:
                    desc = lockgraph._blocking_desc(child, mod)
                    if desc is not None:
                        out.append(Finding(
                            "GL703", mod.relpath, child.lineno,
                            f"blocking {desc} inside `async def` stalls "
                            "the whole event loop (await the async "
                            "equivalent or use run_in_executor)",
                            fn.qualname))
            visit(child, now_lock, now_await)

    visit(fn.node, None, False)
    return out


def check(project: Project) -> List[Finding]:
    model = lockgraph.get_model(project)
    out: List[Finding] = []
    for mod in project.modules.values():
        for fn in mod.functions:
            if isinstance(fn.node, ast.AsyncFunctionDef):
                out.extend(_scan_async_fn(fn, model))
    return out
