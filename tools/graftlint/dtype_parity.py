"""GL5xx — dtype-parity lint (ops/ only).

The int8/uint8 distance paths owe their exactness to int32-accumulating
MXU dots (`preferred_element_type`), and int16 to the high/low byte split
(ops/distance.py module docstring): converting integer VECTORS to float32
before the contraction silently reintroduces per-product f32 rounding —
the exact bug that cost direction-B int16 recall 0.934 vs the reference
(reports/AB_REFERENCE.md).  Upcasting dot RESULTS (e.g. the weighted
recombination in `_int16_parts_f32`) is fine; upcasting INPUTS is not.

Rule:

* GL501 — inside an ops/ function that handles integer dtypes, a value
  produced by `.astype(float32)` / `.astype(jnp.float32)` flows into a
  dot-like contraction (`dot`, `dot_general`, `matmul`, `einsum`,
  `tensordot`, `@`).  Breaks exact-arithmetic parity with the reference.
"""

from __future__ import annotations

import ast
from typing import List, Set

from tools.graftlint.core import Finding, ModuleInfo, Project, _dotted

RULES = {
    "GL501": "integer distance path upcasts vectors to float32 before "
             "the dot (breaks exact-arithmetic parity)",
}

_SCOPE = "ops/"
_DOT_CALLS = {"dot", "dot_general", "matmul", "einsum", "tensordot", "vdot"}
_INT_TOKENS = ("int8", "uint8", "int16")


def _is_f32_astype(node: ast.AST) -> bool:
    """`x.astype(jnp.float32)` / `x.astype(np.float32)` / `.astype("float32")`."""
    if not (isinstance(node, ast.Call) and
            isinstance(node.func, ast.Attribute) and
            node.func.attr == "astype" and node.args):
        return False
    arg = node.args[0]
    d = _dotted(arg)
    if d and d.split(".")[-1] == "float32":
        return True
    return isinstance(arg, ast.Constant) and arg.value == "float32"


def _mentions_int_dtype(fn_node: ast.AST) -> bool:
    for node in ast.walk(fn_node):
        d = _dotted(node) if isinstance(node, ast.Attribute) else None
        if d and d.split(".")[-1] in _INT_TOKENS:
            return True
        if isinstance(node, ast.Constant) and \
                isinstance(node.value, str) and node.value in _INT_TOKENS:
            return True
    return False


def _check_function(mod: ModuleInfo, fn) -> List[Finding]:
    if not _mentions_int_dtype(fn.node):
        return []
    out: List[Finding] = []
    upcast_names: Set[str] = set()
    upcast_lines = {}

    # pass 1: names assigned from an f32 astype
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Assign) and _is_f32_astype(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    upcast_names.add(tgt.id)
                    upcast_lines[tgt.id] = node.lineno

    def feeds_upcast(arg: ast.AST) -> bool:
        if _is_f32_astype(arg):
            return True
        return isinstance(arg, ast.Name) and arg.id in upcast_names

    # pass 2: dot-like calls and matmul operators taking an upcast input
    for node in ast.walk(fn.node):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            leaf = d.split(".")[-1] if d else ""
            if leaf in _DOT_CALLS:
                for arg in list(node.args) + \
                        [k.value for k in node.keywords]:
                    if feeds_upcast(arg):
                        out.append(Finding(
                            "GL501", mod.relpath, node.lineno,
                            f"float32-upcast vector feeds `{leaf}` in an "
                            "integer distance path — use an int32-"
                            "accumulating dot (preferred_element_type) "
                            "to keep exact parity", fn.qualname))
                        break
        elif isinstance(node, ast.BinOp) and \
                isinstance(node.op, ast.MatMult):
            if feeds_upcast(node.left) or feeds_upcast(node.right):
                out.append(Finding(
                    "GL501", mod.relpath, node.lineno,
                    "float32-upcast vector feeds `@` in an integer "
                    "distance path — use an int32-accumulating dot "
                    "to keep exact parity", fn.qualname))
    return out


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for relpath, mod in project.modules.items():
        if _SCOPE not in relpath:
            continue
        for fn in mod.functions:
            out.extend(_check_function(mod, fn))
    return out
