"""GL411 — persistence writes must ride the atomic-write/WAL helpers.

The durability contract (ISSUE 9, DESIGN.md §15) is that every byte the
index persistence subsystem puts on disk is either fsync'd before a
rename publishes it (io/atomic.py) or logged through the checksummed
WAL (io/wal.py).  A bare write-mode ``open()`` in a save path relies on
close-time flushing — the exact implicit contract that loses acked
writes on power loss and leaves truncated blobs behind a valid-looking
``indexloader.ini``.

Rule:

* GL411 — a call to builtin ``open()`` with a write-capable mode
  (``w``, ``a``, ``x`` or ``+``) in sptag_tpu/core/ or sptag_tpu/io/,
  outside the two sanctioned helper modules (io/atomic.py, io/wal.py).
  Read-mode opens and ``os.open``-style attribute calls are out of
  scope; so are algo//serve//utils (their writes are staged files and
  caches whose durability the core save path already owns — algo's
  ``_save_index_data`` implementations route through
  ``atomic.checked_open`` by convention, enforced by the crash-matrix
  tests rather than this rule).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.graftlint.core import Finding, ModuleInfo, Project

RULES = {
    "GL411": "persistence write bypasses the atomic-write/WAL helpers "
             "(bare write-mode open() in core//io — use "
             "io.atomic.checked_open / io.wal)",
}

_SCOPES = ("sptag_tpu/core/", "sptag_tpu/io/")
_HELPERS = ("sptag_tpu/io/atomic.py", "sptag_tpu/io/wal.py")

_WRITE_CHARS = set("wax+")


def _mode_of(call: ast.Call) -> Optional[str]:
    """The literal mode argument of an open() call, None when absent or
    not a string constant (a computed mode is flagged conservatively —
    see _check_module)."""
    if len(call.args) >= 2:
        node = call.args[1]
    else:
        node = next((kw.value for kw in call.keywords
                     if kw.arg == "mode"), None)
    if node is None:
        return "r"          # open() default
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None             # computed — can't prove it's read-only


def _enclosing(mod: ModuleInfo, lineno: int) -> str:
    best = ""
    best_line = -1
    for fn in mod.functions:
        end = getattr(fn.node, "end_lineno", fn.node.lineno)
        if fn.node.lineno <= lineno <= end and fn.node.lineno > best_line:
            best, best_line = fn.qualname, fn.node.lineno
    return best


def _check_module(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if not (isinstance(node.func, ast.Name)
                and node.func.id == "open"):
            continue
        mode = _mode_of(node)
        if mode is not None and not (_WRITE_CHARS & set(mode)):
            continue        # provably read-only
        out.append(Finding(
            "GL411", mod.relpath, node.lineno,
            f"write-mode open({mode!r} mode) bypasses the atomic-write/"
            "WAL helpers — route through io.atomic.checked_open (fsync "
            "+ fault hooks) or io.wal", _enclosing(mod, node.lineno)))
    return out


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for relpath, mod in project.modules.items():
        if relpath in _HELPERS or any(
                relpath.endswith(h) for h in _HELPERS):
            continue
        if any(relpath.startswith(s) or ("/" + s) in relpath
               for s in _SCOPES):
            out.extend(_check_module(mod))
    return out
