"""`python -m tools.graftlint [paths...]` — see runner.main."""

import sys

from tools.graftlint.runner import main

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
