"""graftlint runner — checker registry, project lint entry points, CLI.

`lint_project(root)` is the programmatic surface tests use;
`main(argv)` is `python -m tools.graftlint sptag_tpu/`.
Exit codes: 0 = clean (all findings baseline-suppressed), 1 = new
unsuppressed findings, 2 = usage / baseline-format error.
"""

from __future__ import annotations

import argparse
import os
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

from tools.graftlint import (asyncrules, attrmodel, concurrency, costrules,
                             dtype_parity, errorpath, guardedby, hostsync,
                             lockgraph, obsgraph, obsnames, persistrules,
                             retrace, tracecontract)
from tools.graftlint.baseline import (BaselineError, Suppression,
                                      apply_baseline, load_baseline)
from tools.graftlint.core import Finding, Project

CHECKERS = (hostsync, retrace, concurrency, errorpath, dtype_parity,
            obsnames, lockgraph, asyncrules, costrules, persistrules,
            guardedby, tracecontract, attrmodel, obsgraph)

#: rule id -> one-line description, collected from every checker module
ALL_RULES: Dict[str, str] = {}
for _mod in CHECKERS:
    ALL_RULES.update(_mod.RULES)

DEFAULT_BASELINE = os.path.join(os.path.dirname(__file__), "baseline.toml")


def run_checkers(project: Project,
                 select: Optional[Sequence[str]] = None) -> List[Finding]:
    """All findings (plus parse errors), sorted by location.  `select`
    restricts to rule-id prefixes (e.g. ["GL3"] or ["GL301"])."""
    findings: List[Finding] = list(project.errors)
    for checker in CHECKERS:
        if select and not any(rule.startswith(s)
                              for rule in checker.RULES
                              for s in select):
            continue          # no selected rule — skip the whole pass
        findings.extend(checker.check(project))
    if select:
        findings = [f for f in findings
                    if any(f.rule.startswith(s) for s in select)]
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def lint_project(root: str, baseline_path: Optional[str] = None,
                 select: Optional[Sequence[str]] = None
                 ) -> Tuple[List[Finding], List[Finding],
                            List[Suppression]]:
    """-> (unsuppressed, suppressed, stale_suppressions)."""
    project = Project.from_tree(root)
    findings = run_checkers(project, select=select)
    if baseline_path is None:
        return findings, [], []
    suppressions = load_baseline(baseline_path)
    unsuppressed, suppressed = apply_baseline(findings, suppressions)
    stale = [s for s in suppressions if s.hits == 0]
    return unsuppressed, suppressed, stale


def lint_sources(sources: Dict[str, str],
                 select: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint in-memory sources (the unit-test surface): {relpath: text}."""
    return run_checkers(Project(sources), select=select)


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="graftlint",
        description="TPU/JAX static-analysis suite for sptag_tpu "
                    "(host-sync, retrace, concurrency, error-path, "
                    "dtype-parity, observability-names, lock-order/"
                    "blocking-under-lock, sync-async hazards)")
    parser.add_argument("paths", nargs="*", default=["sptag_tpu"],
                        help="package roots to lint (default: sptag_tpu)")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="accepted-findings file (default: "
                             "tools/graftlint/baseline.toml)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="report every finding, suppressing nothing")
    parser.add_argument("--select", action="append", default=None,
                        metavar="RULE",
                        help="only run rules with this id prefix "
                             "(repeatable, e.g. --select GL1)")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--schema-dump", action="store_true",
                        help="boot a server+aggregator in-process with "
                             "all telemetry armed, scrape every surface, "
                             "and diff the live exposition against the "
                             "static ObsModel (both directions)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in sorted(ALL_RULES):
            print(f"{rule}  {ALL_RULES[rule]}")
        return 0

    if args.schema_dump:
        from tools.graftlint import schemadump
        return schemadump.main(args.paths or ["sptag_tpu"])

    baseline_path = None if args.no_baseline else args.baseline
    if baseline_path is not None and not os.path.exists(baseline_path):
        if baseline_path != DEFAULT_BASELINE:
            # an EXPLICIT --baseline that does not exist is a usage
            # error — silently linting baseline-less would misreport
            # every accepted finding as a new regression
            print(f"graftlint: baseline file not found: {baseline_path}",
                  file=sys.stderr)
            return 2
        baseline_path = None

    # lint every root first, THEN apply the baseline once over the
    # combined findings — per-root application would double-load the
    # suppressions and misreport entries satisfied by another root as
    # stale
    t0 = time.monotonic()
    findings: List[Finding] = []
    for root in (args.paths or ["sptag_tpu"]):
        if not os.path.isdir(root):
            print(f"graftlint: no such directory: {root}", file=sys.stderr)
            return 2
        findings.extend(run_checkers(Project.from_tree(root),
                                     select=args.select))
    stale: List[Suppression] = []
    total_suppressed = 0
    total_unsuppressed = findings
    if baseline_path is not None:
        try:
            suppressions = load_baseline(baseline_path)
        except BaselineError as e:
            print(f"graftlint: {e}", file=sys.stderr)
            return 2
        total_unsuppressed, suppressed = apply_baseline(findings,
                                                        suppressions)
        total_suppressed = len(suppressed)
        # under --select, only suppressions for the selected rules can
        # meaningfully be stale — the others never had a chance to match
        stale = [s for s in suppressions if s.hits == 0
                 and (not args.select
                      or any(s.rule.startswith(p) for p in args.select))]

    for f in total_unsuppressed:
        print(f.format())
    for s in stale:
        print(f"graftlint: note: stale baseline entry "
              f"({s.rule} {s.path} {s.symbol or '*'}) matched nothing — "
              "prune it", file=sys.stderr)
    n = len(total_unsuppressed)
    elapsed = time.monotonic() - t0
    print(f"graftlint: {n} finding(s), {total_suppressed} "
          f"baseline-suppressed, {len(stale)} stale baseline entr"
          f"{'y' if len(stale) == 1 else 'ies'} in {elapsed:.2f}s",
          file=sys.stderr)
    return 1 if total_unsuppressed else 0
