"""GL4xx — error-path lint (serve/ and core/ only).

The serving boundary's contract is: every failure either becomes an
`ErrorCode` / an error-status wire reply, or is logged with a stack.  A
handler that swallows an exception silently turns a data-loss bug into a
"recall looks a bit low" mystery.  Scope is deliberately narrow — serve/
and core/ are the error-code boundaries; kernels and tools keep their
idioms (best-effort cleanup `except OSError: pass` is ACCEPTED there via
the baseline, with a justification naming why it is best-effort).

Rules:

* GL401 — bare `except:` — catches SystemExit/KeyboardInterrupt too;
  catch a type.
* GL402 — a swallowed exception: the handler neither re-raises, nor logs,
  nor returns/yields a value, nor references `ErrorCode` — its body is
  pure no-op (pass / constant assignment / `continue`).
"""

from __future__ import annotations

import ast
from typing import List

from tools.graftlint.core import Finding, ModuleInfo, Project, _dotted

RULES = {
    "GL401": "bare `except:` (catches SystemExit/KeyboardInterrupt)",
    "GL402": "swallowed exception: handler neither raises, logs, returns "
             "a value, nor produces an ErrorCode",
}

_SCOPES = ("sptag_tpu/serve/", "sptag_tpu/core/")

_LOG_METHODS = {"exception", "warning", "error", "critical", "info",
                "debug", "log"}


def _handler_is_meaningful(handler: ast.ExceptHandler) -> bool:
    """Does the handler DO anything with the failure?  Meaningful =
    re-raise, return/yield a result, break/continue a retry loop, call
    anything (logging, cleanup, state transition), assign object state
    (`self.x = None` connection resets), or reference ErrorCode.  What
    remains — `pass` and local constant assignments — is a swallow."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Yield,
                             ast.YieldFrom, ast.Break, ast.Continue,
                             ast.Call)):
            return True
        if isinstance(node, ast.Name) and node.id == "ErrorCode":
            return True
        if isinstance(node, ast.Assign) and any(
                isinstance(t, (ast.Attribute, ast.Subscript))
                for t in node.targets):
            return True
    return False


def _check_module(mod: ModuleInfo) -> List[Finding]:
    out: List[Finding] = []
    # map line -> enclosing function qualname for symbol attribution
    def enclosing(lineno: int) -> str:
        best = ""
        best_line = -1
        for fn in mod.functions:
            end = getattr(fn.node, "end_lineno", fn.node.lineno)
            if fn.node.lineno <= lineno <= end and \
                    fn.node.lineno > best_line:
                best, best_line = fn.qualname, fn.node.lineno
        return best

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append(Finding(
                "GL401", mod.relpath, node.lineno,
                "bare `except:` catches SystemExit/KeyboardInterrupt — "
                "name the exception type", enclosing(node.lineno)))
            continue
        if not _handler_is_meaningful(node):
            caught = _dotted(node.type) or "…"
            out.append(Finding(
                "GL402", mod.relpath, node.lineno,
                f"`except {caught}` swallows the failure (no raise / log "
                "/ return / ErrorCode) — convert to an ErrorCode or log "
                "it", enclosing(node.lineno)))
    return out


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for relpath, mod in project.modules.items():
        if any(relpath.startswith(s) or ("/" + s) in relpath
               for s in _SCOPES):
            out.extend(_check_module(mod))
    return out
