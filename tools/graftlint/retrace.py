"""GL2xx — retrace lint.

XLA compiles one executable per (structure, static-args, shapes) signature.
A Python scalar passed as a TRACED argument hashes by value, so every new
value mints a fresh trace; shape-dependent branching inside a jitted body
retraces per shape.  Both are invisible in tests (small value sets) and
fatal in a long-lived server (unbounded compile-cache growth — the exact
failure `serve/service.py:_sanitize_max_check` quantizes against).

Rules:

* GL201 — a `jax.jit` / `shard_map` root has a parameter whose annotation
  or default marks it as a Python scalar (int/bool/str/float), but the
  name is not listed in `static_argnames`.  Every distinct value
  recompiles; declare it static or pass it as an array.
* GL202 — an f-string inside a jitted body: it evaluates at TRACE time
  (once per compile, against abstract values), which is almost never the
  intent — and interpolating a tracer embeds `Traced<...>` garbage.
* GL203 — `if` / `while` branching on `.shape` / `.ndim` inside a jitted
  body: legal (shapes are static) but each distinct shape compiles a new
  program.  Intentional shape specialization belongs in the baseline
  with a justification.
"""

from __future__ import annotations

import ast
from typing import List

from tools.graftlint.core import (
    Finding,
    FunctionInfo,
    Project,
    body_nodes,
)

RULES = {
    "GL201": "scalar parameter of a jit/shard_map root not declared in "
             "static_argnames (recompile per value)",
    "GL202": "f-string inside a jitted body (evaluates at trace time)",
    "GL203": "shape-dependent `if`/`while` inside a jitted body "
             "(recompile per shape)",
}

_SCALAR_ANNOTATIONS = {"int", "bool", "str", "float"}


def _scalar_params(fn: FunctionInfo) -> List[tuple]:
    """(name, why) for params whose annotation or default is a Python
    scalar.  `None` defaults are excluded: they are array-or-absent
    sentinels in this codebase, not scalar config."""
    a = fn.node.args
    params = list(a.posonlyargs) + list(a.args) + list(a.kwonlyargs)
    defaults = [None] * (len(a.posonlyargs) + len(a.args)
                         - len(a.defaults)) + list(a.defaults) + \
        list(a.kw_defaults)
    out = []
    for p, d in zip(params, defaults):
        if p.arg == "self":
            continue
        ann = p.annotation
        if isinstance(ann, ast.Name) and ann.id in _SCALAR_ANNOTATIONS:
            out.append((p.arg, f"annotated `{ann.id}`"))
            continue
        if isinstance(d, ast.Constant) and \
                isinstance(d.value, (bool, int, float, str)):
            out.append((p.arg, f"default `{d.value!r}`"))
    return out


def _shape_dependent(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in ("shape", "ndim"):
            return True
    return False


def check(project: Project) -> List[Finding]:
    out: List[Finding] = []
    for mod in project.modules.values():
        for fn in mod.functions:
            # GL201 — roots only (that is where static_argnames lives)
            if fn.is_jit_root or fn.is_shard_root:
                for name, why in _scalar_params(fn):
                    if name in fn.static_args:
                        continue
                    kind = "shard_map" if fn.is_shard_root else "jax.jit"
                    out.append(Finding(
                        "GL201", mod.relpath, fn.line,
                        f"{kind} root parameter `{name}` ({why}) is not "
                        "in static_argnames — every distinct value "
                        "recompiles", fn.qualname))
            if not fn.jit_reachable:
                continue
            for node in body_nodes(fn):
                if isinstance(node, ast.JoinedStr):
                    out.append(Finding(
                        "GL202", mod.relpath, node.lineno,
                        "f-string inside a jitted body evaluates at "
                        "trace time, not per call", fn.qualname))
                elif isinstance(node, (ast.If, ast.While)) and \
                        _shape_dependent(node.test):
                    kw = "if" if isinstance(node, ast.If) else "while"
                    out.append(Finding(
                        "GL203", mod.relpath, node.lineno,
                        f"`{kw}` on `.shape`/`.ndim` inside a jitted "
                        "body compiles one program per shape",
                        fn.qualname))
    return out
