# Parity with the reference Dockerfile (build + test in one container).
# CPU image: the TPU runtime is provided by the deployment environment
# (libtpu + a real chip); this image runs the full test suite on the CPU
# backend with a virtual 8-device mesh.
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ make && rm -rf /var/lib/apt/lists/*

WORKDIR /app
COPY . .

RUN pip install --no-cache-dir "jax[cpu]" numpy pytest hypothesis
RUN g++ -O2 -shared -fPIC -std=c++17 -pthread \
    -o native/libsptag_host.so native/sptag_host.cpp

RUN python -m pytest tests/ -q

CMD ["python", "-m", "sptag_tpu.serve.server", "--help"]
