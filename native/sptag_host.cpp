// sptag_tpu native host components.
//
// The reference keeps its whole runtime in C++; in the TPU-native design the
// device math lives in XLA/Pallas and the host runtime stays native where
// the reference's is performance-critical.  This library provides:
//
//  * the parallel TSV ingestion parser — parity with
//    Helper::DefaultReader's block subtasks
//    (/root/reference/AnnService/src/Helper/VectorSetReaders/
//    DefaultReader.cpp:200-320): "<meta>\t<v1>|<v2>|...\n" lines parsed
//    into a row-major float32 matrix + metadata offsets, one block per
//    thread;
//  * the wire packet-header codec (inc/Socket/Packet.h:52-76) for
//    high-throughput serving front doors.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this toolchain).
//
// Build: g++ -O3 -march=native -shared -fPIC -o libsptag_host.so
//        sptag_host.cpp -lpthread

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- TSV parse

// Pass 1: count data lines (non-empty) in [buf, buf+len).
long long sptag_count_lines(const char* buf, long long len) {
    long long rows = 0;
    const char* end = buf + len;
    const char* p = buf;
    while (p < end) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* line_end = nl ? nl : end;
        if (line_end > p && !(line_end - p == 1 && *p == '\r')) ++rows;
        p = nl ? nl + 1 : end;
    }
    return rows;
}

namespace {

struct BlockResult {
    long long rows_filled = 0;
    int dim_seen = 0;
    int error = 0;
};

// Parse one block of lines into out[row0*dim ...]; metadata copied into
// meta_buf at meta_offsets[global_row].  Caller sizes out for the counted
// rows and meta_buf for the block's byte length (metadata is never longer
// than its line).
void parse_block(const char* buf, long long len, char delim, int dim,
                 float* out, long long row0,
                 char* meta_buf, long long meta_cap,
                 long long* meta_lens, BlockResult* result) {
    const char* end = buf + len;
    const char* p = buf;
    long long row = row0;
    long long meta_used = 0;
    while (p < end) {
        const char* nl = static_cast<const char*>(
            memchr(p, '\n', static_cast<size_t>(end - p)));
        const char* line_end = nl ? nl : end;
        if (line_end > p && *(line_end - 1) == '\r') --line_end;
        if (line_end <= p) {
            p = nl ? nl + 1 : end;
            continue;
        }
        const char* tab = static_cast<const char*>(
            memchr(p, '\t', static_cast<size_t>(line_end - p)));
        const char* vec_begin = p;
        long long meta_len = 0;
        if (tab) {
            meta_len = tab - p;
            vec_begin = tab + 1;
        }
        if (meta_len > 0 && meta_used + meta_len <= meta_cap) {
            memcpy(meta_buf + meta_used, p, static_cast<size_t>(meta_len));
        }
        meta_lens[row] = meta_len;
        meta_used += meta_len;

        float* out_row = out + row * dim;
        int d = 0;
        const char* q = vec_begin;
        while (q < line_end && d < dim) {
            char* parse_end = nullptr;
            float v = strtof(q, &parse_end);
            if (parse_end == q) break;
            out_row[d++] = v;
            q = parse_end;
            if (q < line_end && *q == delim) ++q;
        }
        if (d != dim) {
            result->error = 1;
            result->dim_seen = d;
            return;
        }
        ++row;
        p = nl ? nl + 1 : end;
    }
    result->rows_filled = row - row0;
}

}  // namespace

// Parallel parse: splits [buf, len) into n_threads blocks on line
// boundaries; fills out (rows x dim float32), meta_blob (concatenated
// metadata bytes, caller-capacity len) and meta_lens (rows).  Returns rows
// parsed, or -1 on malformed input (dimension mismatch).
long long sptag_parse_tsv(const char* buf, long long len, char delim,
                          int dim, int n_threads, float* out,
                          char* meta_blob, long long* meta_lens) {
    if (len <= 0 || dim <= 0) return 0;
    if (n_threads < 1) n_threads = 1;

    // block boundaries on line starts
    std::vector<long long> bounds;
    bounds.push_back(0);
    long long step = len / n_threads;
    for (int i = 1; i < n_threads; ++i) {
        long long want = i * step;
        if (want <= bounds.back()) continue;
        const char* nl = static_cast<const char*>(
            memchr(buf + want, '\n', static_cast<size_t>(len - want)));
        if (!nl) break;
        long long pos = (nl - buf) + 1;
        if (pos > bounds.back() && pos < len) bounds.push_back(pos);
    }
    bounds.push_back(len);

    const size_t n_blocks = bounds.size() - 1;
    std::vector<long long> row_starts(n_blocks + 1, 0);
    for (size_t b = 0; b < n_blocks; ++b) {
        row_starts[b + 1] = row_starts[b]
            + sptag_count_lines(buf + bounds[b], bounds[b + 1] - bounds[b]);
    }

    std::vector<BlockResult> results(n_blocks);
    // per-block metadata staging: block b's metadata is <= its byte length
    std::vector<std::vector<char>> staging(n_blocks);
    std::vector<std::thread> threads;
    threads.reserve(n_blocks);
    for (size_t b = 0; b < n_blocks; ++b) {
        staging[b].resize(static_cast<size_t>(bounds[b + 1] - bounds[b]));
        threads.emplace_back(parse_block, buf + bounds[b],
                             bounds[b + 1] - bounds[b], delim, dim, out,
                             row_starts[b], staging[b].data(),
                             static_cast<long long>(staging[b].size()),
                             meta_lens, &results[b]);
    }
    for (auto& t : threads) t.join();
    for (size_t b = 0; b < n_blocks; ++b) {
        if (results[b].error) return -1;
    }

    // merge pass: concatenate metadata in row order
    long long total_rows = row_starts[n_blocks];
    long long off = 0;
    for (size_t b = 0; b < n_blocks; ++b) {
        long long staged = 0;
        for (long long r = row_starts[b]; r < row_starts[b + 1]; ++r) {
            memcpy(meta_blob + off, staging[b].data() + staged,
                   static_cast<size_t>(meta_lens[r]));
            off += meta_lens[r];
            staged += meta_lens[r];
        }
    }
    return total_rows;
}

// ------------------------------------------------------------ packet codec

// 16-byte header: u8 type, u8 status, u32 bodyLength, u32 connectionID,
// u32 resourceID, 2B pad (inc/Socket/Packet.h:52-76).
void sptag_pack_header(std::uint8_t type, std::uint8_t status,
                       std::uint32_t body_length,
                       std::uint32_t connection_id,
                       std::uint32_t resource_id, std::uint8_t* out16) {
    out16[0] = type;
    out16[1] = status;
    memcpy(out16 + 2, &body_length, 4);
    memcpy(out16 + 6, &connection_id, 4);
    memcpy(out16 + 10, &resource_id, 4);
    out16[14] = 0;
    out16[15] = 0;
}

void sptag_unpack_header(const std::uint8_t* in16, std::uint8_t* type,
                         std::uint8_t* status, std::uint32_t* body_length,
                         std::uint32_t* connection_id,
                         std::uint32_t* resource_id) {
    *type = in16[0];
    *status = in16[1];
    memcpy(body_length, in16 + 2, 4);
    memcpy(connection_id, in16 + 6, 4);
    memcpy(resource_id, in16 + 10, 4);
}

}  // extern "C"
